"""Host-tier ingest micro-benchmarks: the Python hot path (the analog of
the reference's 20M samples/s Go headline) and the native staging buffer.

Usage: python benchmarks/host_ingest.py [--threads 4] [--seconds 2]
"""

from __future__ import annotations

import argparse
import threading
import time

import numpy as np

# runnable from anywhere: add the repo root to sys.path
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--threads", type=int, default=4)
    parser.add_argument("--seconds", type=float, default=2.0)
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from loghisto_tpu import MetricSystem
    from loghisto_tpu import _native

    ms = MetricSystem(interval=3600, sys_stats=False)

    def run_threaded(op, label):
        stop = threading.Event()
        counts = [0] * args.threads

        def worker(k):
            while not stop.is_set():
                op()
                counts[k] += 1

        threads = [
            threading.Thread(target=worker, args=(k,))
            for k in range(args.threads)
        ]
        for t in threads:
            t.start()
        time.sleep(args.seconds)
        stop.set()
        for t in threads:
            t.join()
        rate = sum(counts) / args.seconds
        print(f"{label:>28}: {rate/1e6:>8.2f}M ops/s "
              f"({args.threads} threads)")
        return rate

    run_threaded(lambda: ms.counter("c", 1), "counter")
    run_threaded(lambda: ms.histogram("h", 42.0), "histogram")

    def timer_op():
        ms.start_timer("t").stop()

    run_threaded(timer_op, "start_timer/stop")

    if _native.fastpath_available():
        fast_ms = MetricSystem(
            interval=3600, sys_stats=False, fast_ingest=True
        )
        run_threaded(
            lambda: fast_ms.histogram("h", 42.0), "histogram (fast_ingest)"
        )
        fast_ms.collect_raw_metrics()
    else:
        print("fastpath unavailable:", _native._fastpath_error)

    batch_ids = np.zeros(10_000, dtype=np.int32)
    batch_vals = np.full(10_000, 42.0)

    def batch_op():
        ms.histogram_batch("hb", batch_vals)

    stop = threading.Event()
    n = [0]
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < args.seconds:
        batch_op()
        n[0] += len(batch_vals)
    print(f"{'histogram_batch(10k)':>28}: "
          f"{n[0]/args.seconds/1e6:>8.2f}M samples/s (1 thread)")
    ms.collect_raw_metrics()  # drain

    if _native.available():
        buf = _native.NativeIngestBuffer(
            num_shards=max(4, args.threads), capacity_per_shard=1 << 22
        )
        t0 = time.perf_counter()
        sent = 0
        while time.perf_counter() - t0 < args.seconds:
            buf.record_batch(batch_ids, batch_vals.astype(np.float64))
            sent += len(batch_ids)
            if sent % (1 << 22) == 0:
                buf.drain()
        print(f"{'native record_batch(10k)':>28}: "
              f"{sent/args.seconds/1e6:>8.2f}M samples/s (1 thread)")
        buf.close()
    else:
        print("native staging unavailable:", _native.build_error())


if __name__ == "__main__":
    main()
