"""Fleet-observability overhead receipts (the ISSUE 12 tentpole): what
the cross-process trace/freshness/health plane costs on the federation
fan-in path, and the end-to-end record->queryable latency it measures.

Two runs of the identical 32-emitter x 1k-metric fan-in cell from
benchmarks/federation_bench.py (threads, not processes — the wire path
is identical and a 1-core CI box can't exec 32 interpreters without
measuring mostly spawn overhead):

  baseline  wire v1 frames — the PR-11 format: no capture stamps, no
            health summary, and the receiver skips anchoring, freshness
            accounting, and per-emitter rollup entirely.
  fleet_obs wire v2 frames — capture stamps + piggybacked health JSON
            on every frame; the receiver anchors clocks, completes a
            freshness sample per applied frame, and maintains the
            /fleetz rollup state.

Both runs carry the always-on emitter span ring, so the delta isolates
exactly the fleet-observability plane.  ``fleet_obs_overhead_pct`` is
the fan-in throughput loss (best-of-N per mode to shed scheduler
noise); the PR's acceptance bar is < 2 %.  ``fleet_freshness_p99_us``
is the receiver's fleet-wide record->queryable p99 over the same run
(standalone receivers complete freshness at apply — there is no
snapshot publisher in this topology).

Roofline plausibility guard: fan-in samples/s times bytes/sample is the
implied loopback byte rate; a number above a generous loopback ceiling
(20 GB/s) is physically impossible for this topology and marks the run
suspect rather than reporting it.

Usage: python benchmarks/fleet_obs_bench.py [--samples 524288]
       [--repeats 5] [--out FLEET_OBS_r12.json]
Prints one JSON object (save as FLEET_OBS_r*.json); importable as
``run(...)`` for bench.py's ``fleet_obs_overhead_pct`` /
``fleet_freshness_p99_us`` headline fields.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

BUCKET_LIMIT = 128
BATCH = 4096
N_EMITTERS = 32
N_METRICS = 1_000
LOOPBACK_PEAK_BYTES_PER_S = 2e10


def _cell(wire_version: int, total_samples: int) -> dict:
    """One fan-in run at the fixed 32-emitter shape; returns throughput
    plus (for v2) the receiver's freshness/rollup readings."""
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.federation.emitter import FederationEmitter
    from loghisto_tpu.federation.receiver import FederationReceiver
    from loghisto_tpu.parallel.aggregator import TPUAggregator

    cfg = MetricConfig(bucket_limit=BUCKET_LIMIT)
    agg = TPUAggregator(num_metrics=N_METRICS + 16, config=cfg)
    rx = FederationReceiver(agg, recv_bytes=1 << 18)
    rx.start()

    batches_per_emitter = max(1, total_samples // (N_EMITTERS * BATCH))
    per_emitter = batches_per_emitter * BATCH
    total = per_emitter * N_EMITTERS

    def emit(idx: int, out: dict) -> None:
        e = FederationEmitter(
            ("127.0.0.1", rx.port), interval=3600.0, config=cfg,
            emitter_id=idx + 1,
            backlog_slots=batches_per_emitter + 8,
            wire_version=wire_version,
        )
        rng = np.random.default_rng(idx)
        lids = np.array(
            [e.local_id(f"m{j}") for j in range(N_METRICS)],
            dtype=np.int32,
        )
        for _ in range(batches_per_emitter):
            ids = lids[rng.integers(0, N_METRICS, BATCH)]
            values = rng.lognormal(3.0, 2.0, BATCH).astype(np.float32)
            e.record_batch(ids, values)
            e.flush(heartbeat=False)  # one frame per batch
        ok = e.drain(timeout=600.0)
        out[idx] = (ok, e.samples_shipped, e.bytes_sent)

    results: dict = {}
    threads = [
        threading.Thread(target=emit, args=(i, results))
        for i in range(N_EMITTERS)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    deadline = time.monotonic() + 600.0
    while rx.samples_merged < total and time.monotonic() < deadline:
        time.sleep(0.005)
    agg.wait_transfers()
    wall_s = time.perf_counter() - t0

    assert all(ok for ok, _, _ in results.values()), "emitter drain failed"
    assert rx.samples_merged == total, (rx.samples_merged, total)
    st = rx.stats()
    bytes_per_sample = rx.bytes_received / total
    sps = total / wall_s
    cell = {
        "wire_version": wire_version,
        "emitters": N_EMITTERS,
        "metrics": N_METRICS,
        "samples": total,
        "frames": rx.frames_received,
        "wall_s": round(wall_s, 3),
        "fanin_samples_per_s": round(sps, 1),
        "bytes_per_sample": round(bytes_per_sample, 3),
        "suspect": sps * bytes_per_sample > LOOPBACK_PEAK_BYTES_PER_S,
    }
    if wire_version >= 2:
        cell["freshness_samples"] = st["freshness_samples"]
        cell["freshness_p99_us"] = round(
            rx.fleet_freshness.percentile_host(99.0), 1
        )
        cell["fleet_emitters"] = len(rx.fleet_report()["emitters"])
    rx.stop()
    agg.close()
    return cell


def _paced_cell(seconds: float = 2.0, interval: float = 0.05) -> dict:
    """Interval-paced run for the freshness headline.  The saturated
    cell flushes each batch the moment it's recorded, so its freshness
    collapses to wire transit (~0 against the clock anchor); here the
    emitter's own ticker ships frames, so a sample's record->queryable
    latency includes the staging dwell until its interval's flush —
    what freshness means in production."""
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.federation.emitter import FederationEmitter
    from loghisto_tpu.federation.receiver import FederationReceiver
    from loghisto_tpu.parallel.aggregator import TPUAggregator

    n_emitters = 8
    cfg = MetricConfig(bucket_limit=BUCKET_LIMIT)
    agg = TPUAggregator(num_metrics=N_METRICS + 16, config=cfg)
    rx = FederationReceiver(agg, recv_bytes=1 << 18)
    rx.start()

    def emit(idx: int, out: dict) -> None:
        e = FederationEmitter(
            ("127.0.0.1", rx.port), interval=interval, config=cfg,
            emitter_id=idx + 1,
        )
        e.start()
        rng = np.random.default_rng(idx)
        lids = np.array(
            [e.local_id(f"m{j}") for j in range(N_METRICS)],
            dtype=np.int32,
        )
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            ids = lids[rng.integers(0, N_METRICS, 512)]
            values = rng.lognormal(3.0, 2.0, 512).astype(np.float32)
            e.record_batch(ids, values)
            time.sleep(0.01)
        out[idx] = e.close(drain_timeout=60.0)

    results: dict = {}
    threads = [
        threading.Thread(target=emit, args=(i, results))
        for i in range(n_emitters)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    deadline = time.monotonic() + 60.0
    while rx.stats()["freshness_pending"] and time.monotonic() < deadline:
        time.sleep(0.01)
    st = rx.stats()
    cell = {
        "emitters": n_emitters,
        "interval_s": interval,
        "freshness_samples": st["freshness_samples"],
        "freshness_p50_us": round(
            rx.fleet_freshness.percentile_host(50.0), 1
        ),
        "freshness_p99_us": round(
            rx.fleet_freshness.percentile_host(99.0), 1
        ),
        "drained": all(results.values()),
    }
    rx.stop()
    agg.close()
    return cell


def run(samples_per_cell: int = 1 << 19, repeats: int = 5) -> dict:
    """Alternate baseline/fleet-obs runs, best-of-``repeats`` per mode.
    On a shared/1-core box the run-to-run spread of a 32-thread fan-in
    is far wider than the true plane cost, so the design sheds noise
    three ways: both code paths warm up before any timed run, the
    within-round order flips every round (drift hits both modes
    equally), and each mode reports its best round (the least-preempted
    observation of the same fixed workload)."""
    _cell(1, samples_per_cell // 4)
    _cell(2, samples_per_cell // 4)
    base_cells, obs_cells = [], []
    for r in range(repeats):
        order = (1, 2) if r % 2 == 0 else (2, 1)
        for wv in order:
            (base_cells if wv == 1 else obs_cells).append(
                _cell(wv, samples_per_cell)
            )
        print(
            f"fleet_obs_bench: round {r + 1}/{repeats}: "
            f"v1 {base_cells[-1]['fanin_samples_per_s']:>12.0f} sps, "
            f"v2 {obs_cells[-1]['fanin_samples_per_s']:>12.0f} sps",
            file=sys.stderr,
        )
    best_base = max(base_cells, key=lambda c: c["fanin_samples_per_s"])
    best_obs = max(obs_cells, key=lambda c: c["fanin_samples_per_s"])
    overhead_pct = 100.0 * (
        1.0 - best_obs["fanin_samples_per_s"]
        / best_base["fanin_samples_per_s"]
    )
    suspect = best_base["suspect"] or best_obs["suspect"]
    paced = _paced_cell()
    print(
        f"fleet_obs_bench: overhead {overhead_pct:+.2f}%, paced "
        f"freshness p99 {paced['freshness_p99_us']:.0f}us "
        f"over {paced['freshness_samples']} frames",
        file=sys.stderr,
    )
    return {
        "bench": "fleet_obs_overhead",
        "batch": BATCH,
        "bucket_limit": BUCKET_LIMIT,
        "repeats": repeats,
        "baseline": base_cells,
        "fleet_obs": obs_cells,
        "paced": paced,
        "fleet_obs_overhead_pct": (
            None if suspect else round(overhead_pct, 2)
        ),
        "fleet_freshness_p99_us": paced["freshness_p99_us"],
        "wire_bytes_per_sample_delta": round(
            best_obs["bytes_per_sample"] - best_base["bytes_per_sample"], 3
        ),
        "suspect": suspect,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--samples", type=int, default=1 << 19,
                        help="samples per run")
    parser.add_argument("--repeats", type=int, default=5,
                        help="runs per mode (best-of)")
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing CPU")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = run(samples_per_cell=args.samples, repeats=args.repeats)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
