"""r14 paged bucket storage characterization: commit H2D bytes per
interval (dense default vs dense+explicit sparse transport vs paged)
and live-rows-per-GiB of HBM (the 1M-rows-per-chip budget math).

Two honest mechanisms, measured separately:

  * **Wire (H2D bytes/interval)** — the paged backend (on its r14
    host-fold route) PINS the packed sparse-triple transport, so every
    interval ships 12 bytes per *occupied cell*.  The dense default
    starts on the raw transport (8 bytes per *sample*); at the time of
    the r14 capture its one-shot density probe inspected only a
    64Ki-sample prefix, which at 100k+ live rows cannot see
    within-interval cell duplication (the prefix touches each cell at
    most ~once) — the probe read density ~0.9 and the dense default
    stayed raw for the whole run, shipping every duplicate sample.
    (r17 fixed that misread: the probe now folds unique cells over the
    WHOLE item, so a rerun of the 100k point switches the dense
    default to sparse and narrows the headline gap to roughly the
    explicitly-pinned line below.)  The dense aggregator CAN be pinned
    to the sparse transport explicitly; that line is reported too
    (wire parity with paged, up to commit padding), so the reduction
    is attributed to what the r14 storage resolver changes about the
    DEFAULT, not to hiding PR 6.
  * **HBM (live rows/GiB)** — dense spends ``B x 4`` bytes per row
    regardless of occupancy (8193 buckets -> 32 KiB/row, ~32.8k rows
    per GiB); the paged pool spends ~1 page per live sparse row plus
    132 B of page table.  Measured from a populated store's occupancy,
    then extrapolated to the 1M-row config against a simulated
    one-chip HBM budget.

Roofline-guarded like bench.py: measured commit samples/s above the
platform's HBM-RMW cap means broken timing, and the affected ratio is
reported with ``suspect: true`` instead of being laundered into a
headline.  Wire bytes come from the aggregators' own transport
accounting, not wall clocks, so they are timing-independent.

Usage: python benchmarks/paged_store.py [--out FILE]
Prints one JSON object (save as PAGED_STORE_r14.json); importable as
``run(...)`` for bench.py and tests.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

# Wire measurements use a compact bucket axis: H2D bytes are
# bucket-count independent (raw ships 8 B/sample, triples 12 B/cell),
# and the 100k-row dense accumulator at the headline B=8193 would be
# 3.3 GB — pointless for a wire measurement.  HBM math uses the
# headline axis.
WIRE_BUCKET_LIMIT = 512
HBM_BUCKET_LIMIT = 4_096

SAMPLES_PER_ROW = 64   # ~1 sample/s per metric over a 60s interval
BUCKETS_PER_ROW = 4    # tight latency band: adjacent log buckets

# Simulated one-chip HBM budget for the 1M-row demo: 16 GiB (v5e-class
# chip), of which the accumulator may claim at most half — the rest is
# program workspace, staging, and the retention tiers.
HBM_BUDGET_GIB = 16.0
HBM_ACC_FRACTION = 0.5


def _sparse_band_workload(rng, m_rows: int):
    """(ids, values): every row gets SAMPLES_PER_ROW samples landing in
    BUCKETS_PER_ROW adjacent codec buckets (a narrow latency band) —
    the sparse-occupancy regime the paged backend targets."""
    base = rng.integers(0, 400, m_rows)
    ids = np.repeat(np.arange(m_rows, dtype=np.int32), SAMPLES_PER_ROW)
    buckets = (
        base.repeat(SAMPLES_PER_ROW)
        + rng.integers(0, BUCKETS_PER_ROW, len(ids))
    )
    # representative value of codec bucket k (k >= 0): e^(k/100) - 1
    # round-trips through compress() onto exactly bucket k
    values = np.expm1(buckets / 100.0).astype(np.float32)
    perm = rng.permutation(len(ids))
    return ids[perm], values[perm]


def _feed(agg, ids, values, chunk: int = 1 << 20) -> float:
    """Push the workload through record_batch + force-flush; returns
    elapsed seconds (host fold + upload + device commit)."""
    t0 = time.perf_counter()
    for off in range(0, len(ids), chunk):
        agg.record_batch(ids[off:off + chunk], values[off:off + chunk])
    agg.flush(force=True)
    return time.perf_counter() - t0


def _conserved_total(agg) -> int:
    if agg.paged is not None:
        _, _, counts = agg.paged.decode_cells(include_spill=True)
        return int(counts.sum())
    total = int(np.asarray(agg._finalize_acc(agg._acc), dtype=np.int64).sum())
    if agg._spill is not None:
        total += int(agg._spill.sum())
    return total


def measure_wire(m_rows: int, cap: float) -> dict:
    """One simulated interval at m_rows live metrics, three configs."""
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.paging import PagedStoreConfig
    from loghisto_tpu.parallel.aggregator import TPUAggregator

    cfg = MetricConfig(bucket_limit=WIRE_BUCKET_LIMIT)
    rng = np.random.default_rng(m_rows)
    ids, values = _sparse_band_workload(rng, m_rows)
    n = len(ids)
    # every row's pages fit comfortably; +1 for the reserved zero slot
    pool = 1 << max(12, (2 * m_rows - 1).bit_length())
    # one flush per reporting interval (the natural 60s-interval
    # deployment: data only needs to reach the device at commit), so the
    # sparse fold window IS the interval.  Identical for all three
    # configs — the raw wire ships 8 B/sample regardless of fold window,
    # so this choice cannot flatter the dense default's number.
    batch = 1 << max(16, (n - 1).bit_length())

    out = {"rows": m_rows, "samples_per_interval": n}
    for key, kw in (
        ("dense_default", dict(storage="dense")),     # transport="auto"
        ("dense_sparse", dict(storage="dense", transport="sparse")),
        ("paged", dict(storage="paged",
                       paged_config=PagedStoreConfig(pool_pages=pool))),
    ):
        agg = TPUAggregator(
            num_metrics=m_rows, config=cfg, batch_size=batch, **kw
        )
        try:
            elapsed = _feed(agg, ids, values)
            assert _conserved_total(agg) == n  # nothing shed or dropped
            if agg.paged is not None:
                h2d = agg.paged.h2d_bytes  # padded wire actually shipped
            else:
                h2d = agg.transport_stats()["bytes_uploaded"]
            sps = n / elapsed
            out[key] = {
                "transport": agg.transport,
                "probe_density": agg.transport_stats()["probe_density"],
                "h2d_bytes_per_interval": int(h2d),
                "h2d_bytes_per_sample": round(h2d / n, 2),
                "elapsed_s": round(elapsed, 3),
                "measured_samples_per_s": round(sps, 1),
                "suspect": sps > cap,
            }
            if agg.paged is not None:
                out[key]["occupied_pages"] = agg.paged.occupied_pages
                out[key]["storage_reason"] = agg.storage_reason
        finally:
            agg.close()
    out["paged_reduction_vs_dense_default"] = round(
        out["dense_default"]["h2d_bytes_per_interval"]
        / out["paged"]["h2d_bytes_per_interval"], 2
    )
    out["paged_vs_dense_sparse_wire"] = round(
        out["paged"]["h2d_bytes_per_interval"]
        / out["dense_sparse"]["h2d_bytes_per_interval"], 2
    )
    return out


def measure_hbm_occupancy(m_rows: int) -> dict:
    """Populate a paged store at the HEADLINE bucket axis with the same
    per-row band occupancy and read its real page consumption."""
    from loghisto_tpu.paging import PagedStore, PagedStoreConfig

    rng = np.random.default_rng(7 * m_rows)
    pool = 1 << max(12, (2 * m_rows - 1).bit_length())
    store = PagedStore(
        m_rows, HBM_BUCKET_LIMIT,
        config=PagedStoreConfig(pool_pages=pool),
    )
    base = rng.integers(0, 3500, m_rows)
    rows = np.repeat(np.arange(m_rows, dtype=np.int64), BUCKETS_PER_ROW)
    cb = (
        base.repeat(BUCKETS_PER_ROW)
        + np.tile(np.arange(BUCKETS_PER_ROW), m_rows)
    )
    packed = np.stack(
        [rows, cb, np.ones_like(rows)], axis=1
    ).astype(np.int32)
    store.commit(packed)
    assert store.spilled_cells == 0 and store.overflowed_cells == 0
    page_bytes = store.config.page_size * 4
    table_bytes_per_row = store.pages_per_row * 4
    pages_per_row = store.occupied_pages / m_rows
    bytes_per_live_row = pages_per_row * page_bytes + table_bytes_per_row
    dense_bytes_per_row = (2 * HBM_BUCKET_LIMIT + 1) * 4
    return {
        "rows": m_rows,
        "occupied_pages": store.occupied_pages,
        "pages_per_live_row": round(pages_per_row, 3),
        "bytes_per_live_row": round(bytes_per_live_row, 1),
        "dense_bytes_per_row": dense_bytes_per_row,
        "max_live_rows_per_gib": int((1 << 30) // bytes_per_live_row),
        "dense_max_live_rows_per_gib": (1 << 30) // dense_bytes_per_row,
        "hbm_reduction": round(dense_bytes_per_row / bytes_per_live_row, 1),
    }


def one_million_row_config(occ: dict) -> dict:
    """The ROADMAP target, sized from MEASURED per-row occupancy (25%
    pool headroom) against the simulated one-chip budget.  The 1M-row
    page table itself is constructed for real (host side) to prove the
    translate path holds at that M — only the pool size is extrapolated."""
    from loghisto_tpu.paging import PagedStore, PagedStoreConfig

    m = 1_000_000
    pages_needed = int(m * occ["pages_per_live_row"] * 1.25) + 1
    page_bytes = 256 * 4
    pool_bytes = pages_needed * page_bytes
    # real construction at M=1M (host table + a demo-size pool), plus a
    # 10k-row committed slice through the full translate/alloc path
    store = PagedStore(
        m, HBM_BUCKET_LIMIT, config=PagedStoreConfig(pool_pages=1 << 15)
    )
    table_bytes = store.page_table.nbytes
    rng = np.random.default_rng(1)
    rows = rng.choice(m, 10_000, replace=False).astype(np.int64)
    packed = np.stack([
        rows, rng.integers(0, 3500, len(rows)), np.ones(len(rows), np.int64)
    ], axis=1).astype(np.int32)
    applied = store.commit(packed)
    assert applied == len(rows)
    paged_gib = (pool_bytes + table_bytes) / (1 << 30)
    dense_gib = m * occ["dense_bytes_per_row"] / (1 << 30)
    budget_gib = HBM_BUDGET_GIB * HBM_ACC_FRACTION
    return {
        "rows": m,
        "pool_pages": pages_needed,
        "pool_gib": round(pool_bytes / (1 << 30), 2),
        "page_table_gib": round(table_bytes / (1 << 30), 2),
        "paged_hbm_gib": round(paged_gib, 2),
        "dense_hbm_gib": round(dense_gib, 2),
        "hbm_budget_gib": budget_gib,
        "fits_one_chip": paged_gib <= budget_gib,
        "dense_fits_one_chip": dense_gib <= budget_gib,
        "demonstrated_table_rows": m,
        "demonstrated_committed_rows": len(rows),
    }


def run(wire_rows=(10_000, 100_000), occupancy_rows: int = 100_000) -> dict:
    import jax

    from bench import plausibility_cap_samples_per_s

    platform = jax.devices()[0].platform
    cfg_bytes = 0
    result = {
        "metric": (
            "paged vs dense bucket storage: commit H2D bytes/interval "
            "and live metric rows per GiB of HBM"
        ),
        "platform": platform,
        "page_size": 256,
        "wire_bucket_limit": WIRE_BUCKET_LIMIT,
        "hbm_bucket_limit": HBM_BUCKET_LIMIT,
        "samples_per_row": SAMPLES_PER_ROW,
        "buckets_per_row": BUCKETS_PER_ROW,
        "configs": {},
    }
    suspect = False
    for m in wire_rows:
        cfg_bytes = m * (2 * WIRE_BUCKET_LIMIT + 1) * 4
        cap = plausibility_cap_samples_per_s(platform, cfg_bytes)
        line = measure_wire(m, cap)
        line["roofline_cap_samples_per_s"] = cap
        result["configs"][str(m)] = line
        suspect = suspect or any(
            line[k]["suspect"]
            for k in ("dense_default", "dense_sparse", "paged")
        )

    occ = measure_hbm_occupancy(occupancy_rows)
    result["hbm_occupancy"] = occ
    result["one_million_rows"] = one_million_row_config(occ)

    # headline fields (bench.py lifts these verbatim)
    biggest = str(max(wire_rows))
    big = result["configs"][biggest]
    result["paged_h2d_bytes_per_interval"] = (
        big["paged"]["h2d_bytes_per_interval"]
    )
    result["dense_default_h2d_bytes_per_interval"] = (
        big["dense_default"]["h2d_bytes_per_interval"]
    )
    result["h2d_reduction_at_rows"] = int(biggest)
    result["h2d_reduction"] = big["paged_reduction_vs_dense_default"]
    result["max_live_rows_per_gib"] = occ["max_live_rows_per_gib"]
    result["dense_max_live_rows_per_gib"] = occ["dense_max_live_rows_per_gib"]
    result["suspect"] = suspect
    return result


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--rows", type=int, nargs="*", default=[10_000, 100_000],
        help="live-row points for the wire measurement",
    )
    args = ap.parse_args()
    result = run(wire_rows=tuple(args.rows))
    text = json.dumps(result, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)


if __name__ == "__main__":
    main()
