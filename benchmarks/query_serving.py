"""Sustained selector-serving under live commits + label churn (the
PR-16 tentpole's receipts): a closed-loop multi-threaded harness where
N query threads hammer brace selectors against a TimeWheel while a
committer thread keeps committing fresh intervals AND churning the
label population (lifecycle evictions + new label sets, so the
registry generation keeps bumping and the inverted index keeps
re-validating).

Every served result is checked against the selector's own predicate:
a row whose name does not satisfy the selector would mean a stale-id
serve (an index entry surviving a generation bump, or a freed slot's
new name leaking an old row) — the harness counts those and the run
only "meets_slo" at >= 1k aggregate QPS with ZERO stale serves.

The serving path under test is the snapshot query engine: warm repeats
inside one interval are host result-cache hits, the first query after
each commit pays one sparse gather dispatch, and every churn commit
additionally pays the index rebuild (generation bump -> full re-index,
the worst case for the label layer).  A separate one-shot leg times
``query_group_by`` (gather + segment-sum + rank search) at each shape.

Usage: python benchmarks/query_serving.py [--duration 2.0]
       [--threads 8] [--full] [--out QUERY_SERVING_r16.json]
Prints one JSON object; importable as ``run(...)`` for bench.py's
headline (query_serving_qps / query_serve_p99_us).
"""

from __future__ import annotations

import argparse
import datetime as _dt
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

# (label, rows, bucket_limit, tiers, churn_every) — the 100k point
# shrinks buckets/tier depth so the rings fit everywhere and churns
# less often (every rebuild is O(rows)); it only runs with --full/TPU.
CONFIGS = [
    ("1000", 1_000, 128, ((8, 1), (4, 8)), 1),
    ("10000", 10_000, 64, ((6, 1), (3, 8)), 2),
    ("100000", 100_000, 32, ((4, 1),), 4),
]

ROUTES = 8
CODES = ("200", "204", "500", "503")
QPS_TARGET = 1_000.0


def _base(i: int) -> str:
    return f"svc{i}.latency"


def _canon(base: str, route: int, code: str) -> str:
    return f"{base};code={code};route=/r{route}"


def _build(rows: int, bucket_limit: int, tiers):
    from loghisto_tpu.commit import IntervalCommitter
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.labels import LabelIndex
    from loghisto_tpu.lifecycle import LifecycleConfig, LifecycleManager
    from loghisto_tpu.parallel.aggregator import TPUAggregator
    from loghisto_tpu.window import TimeWheel

    per_base = ROUTES * len(CODES)
    nbases = max(1, rows // per_base)
    cfg = MetricConfig(bucket_limit=bucket_limit)
    agg = TPUAggregator(num_metrics=rows, config=cfg)
    wheel = TimeWheel(num_metrics=rows, config=cfg, interval=1.0,
                      tiers=tiers, registry=agg.registry)
    wheel.label_index = LabelIndex(agg.registry)
    lc = LifecycleManager(
        agg, wheel,
        LifecycleConfig(check_every=1 << 30,
                        auto_compact_fragmentation=0.0),
    )
    committer = IntervalCommitter(agg, wheel, lifecycle=lc)
    committer.warmup()
    names = []
    for b in range(nbases):
        for r in range(ROUTES):
            for c in CODES:
                if len(names) >= rows:
                    break
                names.append(_canon(_base(b), r, c))
    for n in names:
        agg.registry.id_for(n)
    return committer, agg, wheel, lc, names, nbases


def _interval(rng, i, names, bucket_limit, touch_frac=0.05):
    from loghisto_tpu.metrics import RawMetricSet

    t0 = _dt.datetime(2026, 1, 1, tzinfo=_dt.timezone.utc)
    k = max(1, int(len(names) * touch_frac))
    picks = rng.choice(len(names), size=k, replace=False)
    hists = {}
    for j in picks:
        b = rng.integers(-bucket_limit, bucket_limit, 4)
        c = rng.integers(1, 30, 4)
        h = {}
        for bb, cc in zip(b, c):
            h[int(bb)] = h.get(int(bb), 0) + int(cc)
        hists[names[j]] = h
    return RawMetricSet(time=t0 + _dt.timedelta(seconds=i), counters={},
                        rates={}, histograms=hists, gauges={},
                        duration=1.0)


def _selectors(nbases: int, thread_id: int):
    """Per-thread selector mix: single-row exacts, per-route fans, and
    one regex tail scan — rotated round-robin, 70/20/10 by weight."""
    from loghisto_tpu.labels import parse_selector

    rng = np.random.default_rng(1000 + thread_id)
    sels = []
    for _ in range(32):
        b = _base(int(rng.integers(nbases)))
        r = int(rng.integers(ROUTES))
        c = CODES[int(rng.integers(len(CODES)))]
        sels.extend([
            f"{b}{{route=/r{r},code={c}}}",
            f"{b}{{route=/r{r},code={c}}}",  # weight exacts heaviest
            f"{b}{{route=/r{r}}}",
        ])
        if len(sels) % 9 == 0:
            sels.append(f"{b}{{code=~5..}}")
    return [(s, parse_selector(s).match_name) for s in sels]


def _serve_loop(wheel, sels, window, stop, out):
    lat, served, stale = [], 0, 0
    i = 0
    while not stop.is_set():
        sel, pred = sels[i % len(sels)]
        i += 1
        t1 = time.perf_counter()
        ws = wheel.query(sel, window=window)
        lat.append(time.perf_counter() - t1)
        served += 1
        for name in ws.metrics:
            if not pred(name):
                stale += 1
    out.append((lat, served, stale))


def _churn(agg, lc, names, next_id: int) -> int:
    """Evict the label set at the rotation head and register a fresh
    one in its place: generation bump + freed-slot reuse, the two index
    invalidation paths, exercised on every churn tick."""
    victim = names[next_id % len(names)]
    mid = agg.registry.lookup(victim)
    if mid is not None:
        lc.evict_ids([mid])
    fresh = f"{victim.rsplit('=', 1)[0]}=/g{next_id}"
    agg.registry.id_for(fresh)
    names[next_id % len(names)] = fresh
    return next_id + 1


def run(duration: float = 2.0, threads: int = 8,
        full: bool = False) -> dict:
    import jax

    platform = jax.devices()[0].platform
    configs = CONFIGS if (full or platform == "tpu") else CONFIGS[:2]
    result = {
        "metric": "sustained selector QPS under live commits + churn",
        "platform": platform,
        "threads": threads,
        "duration_s": duration,
        "qps_target": QPS_TARGET,
        "configs": {},
    }
    for label, rows, bucket_limit, tiers, churn_every in configs:
        committer, agg, wheel, lc, names, nbases = _build(
            rows, bucket_limit, tiers
        )
        rng = np.random.default_rng(0)
        window = float(tiers[0][0] * tiers[0][1]) / 2.0
        wheel.pin_window(window)
        for i in range(3):  # warm: snapshots, jit, plan/glob caches
            committer.commit(_interval(rng, i, names, bucket_limit))
        sels = [_selectors(nbases, t) for t in range(threads)]
        for s, _pred in sels[0][:4]:
            wheel.query(s, window=window)

        stop = threading.Event()
        outs: list = []
        workers = [
            threading.Thread(target=_serve_loop,
                             args=(wheel, sels[t], window, stop, outs),
                             daemon=True)
            for t in range(threads)
        ]
        t0 = time.perf_counter()
        for w in workers:
            w.start()
        interval_i, churn_head, commits = 3, 0, 0
        while time.perf_counter() - t0 < duration:
            committer.commit(
                _interval(rng, interval_i, names, bucket_limit)
            )
            interval_i += 1
            commits += 1
            if commits % churn_every == 0:
                churn_head = _churn(agg, lc, names, churn_head)
            time.sleep(0.005)
        stop.set()
        for w in workers:
            w.join(timeout=10.0)
        elapsed = time.perf_counter() - t0

        lat = np.concatenate([np.asarray(o[0]) for o in outs if o[0]])
        served = sum(o[1] for o in outs)
        stale = sum(o[2] for o in outs)
        qps = served / elapsed

        # one-shot group_by leg at the same shape (own clock: rollups
        # are a different dispatch, not part of the selector headline);
        # two warm calls take the jit compile off the clock
        for r in range(2):
            wheel.query_group_by(f"{_base(r % nbases)}{{}}",
                                 by=["route"], window=window,
                                 percentiles=(0.5, 0.99))
        gb = []
        for r in range(20):
            wheel._result_cache.clear()
            t1 = time.perf_counter()
            wheel.query_group_by(f"{_base(r % nbases)}{{}}",
                                 by=["route"], window=window,
                                 percentiles=(0.5, 0.99))
            gb.append(time.perf_counter() - t1)

        idx_stats = wheel.label_index.stats()
        result["configs"][label] = {
            "rows": rows,
            "queries_served": served,
            "qps": round(qps, 1),
            "serve_median_us": round(float(np.median(lat)) * 1e6, 1),
            "serve_p99_us": round(float(np.percentile(lat, 99)) * 1e6, 1),
            "group_by_p99_us": round(
                float(np.percentile(gb, 99)) * 1e6, 1
            ),
            "commits": commits,
            "churn_evictions": lc.evicted_series,
            "index_rebuilds": idx_stats["rebuilds"],
            "selector_cache_hits": idx_stats["selector_cache_hits"],
            "stale_serves": stale,
            "zero_stale_serves": stale == 0,
            "meets_1k_qps": qps >= QPS_TARGET,
        }
    # headline: the largest shape that ran
    head = result["configs"][configs[-1][0] if (full or platform == "tpu")
                             else "10000"]
    result["query_serving_qps"] = head["qps"]
    result["query_serve_p99_us"] = head["serve_p99_us"]
    result["zero_stale_serves"] = all(
        c["zero_stale_serves"] for c in result["configs"].values()
    )
    result["meets_slo"] = (
        result["zero_stale_serves"]
        and all(c["meets_1k_qps"] for c in result["configs"].values())
    )
    return result


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--full", action="store_true",
                    help="include the 100k-row point off-TPU")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    res = run(duration=args.duration, threads=args.threads,
              full=args.full)
    doc = json.dumps(res, indent=2)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")


if __name__ == "__main__":
    main()
