"""Distributed-step characterization at the headline shape (VERDICT r2
item 4): per-step cost of the full mesh aggregation step — local dense
fold + psum merge over the stream axis + metric-sharded accumulate +
stats — at 10k metrics x 8193 buckets with multi-million-sample batches,
against the single-device step on the same workload.

On the CI/CPU host the 8 "devices" are virtual
(--xla_force_host_platform_device_count=8) and time-slice one core, so
absolute samples/s is not a hardware number; the signal is the
mesh/single per-step ratio, which isolates the extra WORK the
distributed step adds (per-shard zero+fold, psum reduction, halo of
out-of-shard samples) from the kernel itself.  On a real multi-chip TPU
the same harness reports true weak scaling (run with --tpu).

Usage: python benchmarks/mesh_scale.py [--metrics 10000]
       [--bucket-limit 4096] [--batch 4194304] [--reps 3] [--out FILE]
Prints one JSON object; importable as ``run(...)`` for tests/capture.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# must precede the jax import when run standalone
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np


def _timed_step(step, acc, ids, values, reps: int) -> tuple[float, object]:
    """Median per-step seconds, value-fetch timed (stats counts leave the
    device each rep — block_until_ready can lie through the tunnel)."""
    acc, stats = step(acc, ids, values)  # compile + warm
    np.asarray(stats["counts"])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        acc, stats = step(acc, ids, values)
        np.asarray(stats["counts"])
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), acc


def run(num_metrics: int = 10_000, bucket_limit: int = 4_096,
        batch: int = 1 << 22, reps: int = 3,
        shapes: list[dict] | None = None) -> dict:
    import jax
    import jax.numpy as jnp

    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.ops.dispatch import ingest_step_fn, resolve_ingest_path
    from loghisto_tpu.ops.stats import dense_stats
    from loghisto_tpu.parallel.aggregator import (
        make_distributed_step,
        make_sharded_accumulator,
    )
    from loghisto_tpu.parallel.mesh import make_mesh

    cfg = MetricConfig(bucket_limit=bucket_limit)
    devs = jax.devices()
    platform = devs[0].platform
    ps = np.array([0.0, 0.5, 0.99, 0.9999, 1.0], dtype=np.float32)

    rng = np.random.default_rng(0)
    raw = rng.zipf(1.3, size=batch)
    ids = jnp.asarray(((raw - 1) % num_metrics).astype(np.int32))
    values = jnp.asarray(
        rng.lognormal(10.0, 2.0, batch).astype(np.float32)
    )

    result = {
        "platform": platform,
        "n_devices": len(devs),
        "num_metrics": num_metrics,
        "num_buckets": cfg.num_buckets,
        "batch": batch,
        "reps": reps,
        "steps": {},
    }

    # -- single-device reference step: dispatched kernel + stats --
    path = resolve_ingest_path(
        "auto", num_metrics, cfg.num_buckets, platform, batch_size=batch
    )
    kernel = ingest_step_fn(path)

    @jax.jit
    def single_step(acc, ids, values):
        acc = kernel(acc, ids, values, cfg.bucket_limit, cfg.precision)
        return acc, dense_stats(acc, ps, cfg.bucket_limit, cfg.precision)

    acc0 = jnp.zeros((num_metrics, cfg.num_buckets), dtype=jnp.int32)
    t_single, acc_out = _timed_step(single_step, acc0, ids, values, reps)
    del acc_out, acc0
    result["steps"]["single"] = {
        "ingest_path": path,
        "seconds_per_step": round(t_single, 4),
        "samples_per_s": round(batch / t_single, 1),
    }

    # -- mesh steps: sweep the dp(stream) x tp(metric) spectrum --
    n = len(devs)
    if shapes is None:
        shapes = []
        metric = 1
        while metric <= n:
            if n % metric == 0 and num_metrics % metric == 0:
                shapes.append({"stream": n // metric, "metric": metric})
            metric *= 2
    from loghisto_tpu.parallel.aggregator import (
        make_interval_distributed_step,
    )

    for shape in shapes:
        mesh = make_mesh(stream=shape["stream"], metric=shape["metric"])
        step = make_distributed_step(
            mesh, num_metrics, cfg.bucket_limit, ps, batch_size=batch
        )
        acc = make_sharded_accumulator(mesh, num_metrics, cfg.num_buckets)
        t_mesh, acc = _timed_step(step, acc, ids, values, reps)
        del acc
        key = f"stream{shape['stream']}xmetric{shape['metric']}"
        result["steps"][key] = {
            "seconds_per_step": round(t_mesh, 4),
            "samples_per_s": round(batch / t_mesh, 1),
            "vs_single": round(t_mesh / t_single, 3),
        }

        # -- interval-amortized path (VERDICT r3 item 3): collective-free
        # per-batch folds, ONE psum at collect.  Report the per-batch
        # ingest cost (the steady-state number the amortization buys) and
        # the once-per-interval collect cost separately.
        ingest, collect, make_partial = make_interval_distributed_step(
            mesh, num_metrics, cfg.bucket_limit, ps, batch_size=batch
        )
        partial = ingest(make_partial(), ids, values)  # compile + warm
        jax.block_until_ready(partial)
        t_in = []
        for _ in range(reps):
            t0 = time.perf_counter()
            partial = ingest(partial, ids, values)
            jax.block_until_ready(partial)
            t_in.append(time.perf_counter() - t0)
        t_ingest = float(np.median(t_in))
        acc = make_sharded_accumulator(mesh, num_metrics, cfg.num_buckets)
        acc, partial, stats = collect(acc, partial)  # compile + warm
        np.asarray(stats["counts"])
        t_col = []
        for _ in range(reps):
            partial = ingest(partial, ids, values)
            jax.block_until_ready(partial)
            t0 = time.perf_counter()
            acc, partial, stats = collect(acc, partial)
            np.asarray(stats["counts"])
            t_col.append(time.perf_counter() - t0)
        t_collect = float(np.median(t_col))
        del acc, partial, stats
        result["steps"][key + "_interval"] = {
            "ingest_seconds_per_batch": round(t_ingest, 4),
            "collect_seconds": round(t_collect, 4),
            "ingest_samples_per_s": round(batch / t_ingest, 1),
            "ingest_vs_single": round(t_ingest / t_single, 3),
            # effective per-batch cost at 10 batches/interval
            "per_batch_at_10_vs_single": round(
                (t_ingest + t_collect / 10) / t_single, 3
            ),
        }
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--metrics", type=int, default=10_000)
    parser.add_argument("--bucket-limit", type=int, default=4_096)
    parser.add_argument("--batch", type=int, default=1 << 22)
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing virtual-CPU devices")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = run(num_metrics=args.metrics, bucket_limit=args.bucket_limit,
                 batch=args.batch, reps=args.reps)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
