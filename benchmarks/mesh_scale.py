"""Distributed-step characterization at the headline shape (VERDICT r2
item 4): per-step cost of the full mesh aggregation step — local dense
fold + psum merge over the stream axis + metric-sharded accumulate +
stats — at 10k metrics x 8193 buckets with multi-million-sample batches,
against the single-device step on the same workload.

PR-8 adds the interval-commit contenders per mesh shape: the sharded
FUSED committer (one shard_map donated-carry program per interval —
cell deltas psum once over the stream axis, then acc fold + every
tier's open-slot scatter execute shard-local on metric-row-sharded
carries) against the FAN-OUT pipeline on the same sharded state
(bridge-merge + per-tier scatters, what "auto" used to force under a
mesh), plus the single-device fused baseline.  Interval-amortized:
per-interval commit latency, dispatches/interval, and committed
samples/s, with bench.py's HBM-roofline plausibility guard marking
physically impossible rates suspect instead of reporting them.

On the CI/CPU host the 8 "devices" are virtual
(--xla_force_host_platform_device_count=8) and time-slice one core, so
absolute samples/s is not a hardware number; the signal is the
mesh/single per-step ratio, which isolates the extra WORK the
distributed step adds (per-shard zero+fold, psum reduction, halo of
out-of-shard samples) from the kernel itself.  On a real multi-chip TPU
the same harness reports true weak scaling (run with --tpu).

Usage: python benchmarks/mesh_scale.py [--metrics 10000]
       [--bucket-limit 4096] [--batch 4194304] [--reps 3]
       [--commit-only] [--commit-metrics 1024] [--commit-reps 8]
       [--out FILE]
Prints one JSON object (save as MESH_SCALE_r*.json); importable as
``run(...)`` / ``run_commit(...)`` for tests/capture.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# must precede the jax import when run standalone
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np


def _timed_step(step, acc, ids, values, reps: int) -> tuple[float, object]:
    """Median per-step seconds, value-fetch timed (stats counts leave the
    device each rep — block_until_ready can lie through the tunnel)."""
    acc, stats = step(acc, ids, values)  # compile + warm
    np.asarray(stats["counts"])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        acc, stats = step(acc, ids, values)
        np.asarray(stats["counts"])
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), acc


def run(num_metrics: int = 10_000, bucket_limit: int = 4_096,
        batch: int = 1 << 22, reps: int = 3,
        shapes: list[dict] | None = None) -> dict:
    import jax
    import jax.numpy as jnp

    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.ops.dispatch import ingest_step_fn, resolve_ingest_path
    from loghisto_tpu.ops.stats import dense_stats
    from loghisto_tpu.parallel.aggregator import (
        make_distributed_step,
        make_sharded_accumulator,
    )
    from loghisto_tpu.parallel.mesh import make_mesh

    cfg = MetricConfig(bucket_limit=bucket_limit)
    devs = jax.devices()
    platform = devs[0].platform
    ps = np.array([0.0, 0.5, 0.99, 0.9999, 1.0], dtype=np.float32)

    rng = np.random.default_rng(0)
    raw = rng.zipf(1.3, size=batch)
    ids = jnp.asarray(((raw - 1) % num_metrics).astype(np.int32))
    values = jnp.asarray(
        rng.lognormal(10.0, 2.0, batch).astype(np.float32)
    )

    result = {
        "platform": platform,
        # virtual CPU "devices" time-slice one core: absolute rates are
        # not hardware numbers, only the mesh/single ratios are signal
        "suspect": platform != "tpu",
        "n_devices": len(devs),
        "num_metrics": num_metrics,
        "num_buckets": cfg.num_buckets,
        "batch": batch,
        "reps": reps,
        "steps": {},
    }

    # -- single-device reference step: dispatched kernel + stats --
    path = resolve_ingest_path(
        "auto", num_metrics, cfg.num_buckets, platform, batch_size=batch
    )
    kernel = ingest_step_fn(path)

    @jax.jit
    def single_step(acc, ids, values):
        acc = kernel(acc, ids, values, cfg.bucket_limit, cfg.precision)
        return acc, dense_stats(acc, ps, cfg.bucket_limit, cfg.precision)

    acc0 = jnp.zeros((num_metrics, cfg.num_buckets), dtype=jnp.int32)
    t_single, acc_out = _timed_step(single_step, acc0, ids, values, reps)
    del acc_out, acc0
    result["steps"]["single"] = {
        "ingest_path": path,
        "seconds_per_step": round(t_single, 4),
        "samples_per_s": round(batch / t_single, 1),
    }

    # -- mesh steps: sweep the dp(stream) x tp(metric) spectrum --
    n = len(devs)
    if shapes is None:
        shapes = []
        metric = 1
        while metric <= n:
            if n % metric == 0 and num_metrics % metric == 0:
                shapes.append({"stream": n // metric, "metric": metric})
            metric *= 2
    from loghisto_tpu.parallel.aggregator import (
        make_interval_distributed_step,
    )

    for shape in shapes:
        mesh = make_mesh(stream=shape["stream"], metric=shape["metric"])
        step = make_distributed_step(
            mesh, num_metrics, cfg.bucket_limit, ps, batch_size=batch
        )
        acc = make_sharded_accumulator(mesh, num_metrics, cfg.num_buckets)
        t_mesh, acc = _timed_step(step, acc, ids, values, reps)
        del acc
        key = f"stream{shape['stream']}xmetric{shape['metric']}"
        result["steps"][key] = {
            "seconds_per_step": round(t_mesh, 4),
            "samples_per_s": round(batch / t_mesh, 1),
            "vs_single": round(t_mesh / t_single, 3),
        }

        # -- interval-amortized path (VERDICT r3 item 3): collective-free
        # per-batch folds, ONE psum at collect.  Report the per-batch
        # ingest cost (the steady-state number the amortization buys) and
        # the once-per-interval collect cost separately.
        ingest, collect, make_partial = make_interval_distributed_step(
            mesh, num_metrics, cfg.bucket_limit, ps, batch_size=batch
        )
        partial = ingest(make_partial(), ids, values)  # compile + warm
        jax.block_until_ready(partial)
        t_in = []
        for _ in range(reps):
            t0 = time.perf_counter()
            partial = ingest(partial, ids, values)
            jax.block_until_ready(partial)
            t_in.append(time.perf_counter() - t0)
        t_ingest = float(np.median(t_in))
        acc = make_sharded_accumulator(mesh, num_metrics, cfg.num_buckets)
        acc, partial, stats = collect(acc, partial)  # compile + warm
        np.asarray(stats["counts"])
        t_col = []
        for _ in range(reps):
            partial = ingest(partial, ids, values)
            jax.block_until_ready(partial)
            t0 = time.perf_counter()
            acc, partial, stats = collect(acc, partial)
            np.asarray(stats["counts"])
            t_col.append(time.perf_counter() - t0)
        t_collect = float(np.median(t_col))
        del acc, partial, stats

        # -- r13 async stream psum: issue the collective via
        # collect.start (no fresh-partial output, so the next interval's
        # fold is not a data-dependent consumer), overlap the next
        # batch's shard-local fold, then fetch.  Compare against the
        # serial collect-then-ingest pair measured above.
        acc = make_sharded_accumulator(mesh, num_metrics, cfg.num_buckets)
        partial = ingest(make_partial(), ids, values)
        jax.block_until_ready(partial)
        acc, stats = collect.start(acc, partial)  # compile + warm
        np.asarray(stats["counts"])
        t_ov = []
        for _ in range(reps):
            partial = ingest(make_partial(), ids, values)
            jax.block_until_ready(partial)
            t0 = time.perf_counter()
            acc, stats = collect.start(acc, partial)
            nxt = ingest(make_partial(), ids, values)  # overlaps the psum
            np.asarray(stats["counts"])
            jax.block_until_ready(nxt)
            t_ov.append(time.perf_counter() - t0)
        t_overlap = float(np.median(t_ov))
        del acc, partial, nxt, stats
        t_serial_pair = t_collect + t_ingest

        result["steps"][key + "_interval"] = {
            "ingest_seconds_per_batch": round(t_ingest, 4),
            "collect_seconds": round(t_collect, 4),
            "ingest_samples_per_s": round(batch / t_ingest, 1),
            "ingest_vs_single": round(t_ingest / t_single, 3),
            # effective per-batch cost at 10 batches/interval
            "per_batch_at_10_vs_single": round(
                (t_ingest + t_collect / 10) / t_single, 3
            ),
            # collect + next batch, serial vs collective-overlapped
            "collect_plus_batch_serial_seconds": round(t_serial_pair, 4),
            "collect_plus_batch_overlap_seconds": round(t_overlap, 4),
            "async_psum_saving_pct": round(
                100.0 * (1.0 - t_overlap / max(t_serial_pair, 1e-9)), 1
            ),
        }
    return result


def _commit_intervals(rng, n, num_metrics, bucket_limit,
                      cells_per_metric=24):
    """Pre-built sparse interval payloads — identical streams for every
    contender (mirrors benchmarks/interval_commit.py)."""
    import datetime as _dt

    t0 = _dt.datetime(2026, 1, 1, tzinfo=_dt.timezone.utc)
    names = [f"m{i}" for i in range(num_metrics)]
    out = []
    for i in range(n):
        hists = {}
        for name in names:
            b = rng.integers(-bucket_limit, bucket_limit, cells_per_metric)
            c = rng.integers(1, 100, cells_per_metric)
            h = {}
            for bb, cc in zip(b, c):
                h[int(bb)] = h.get(int(bb), 0) + int(cc)
            hists[name] = h
        out.append((t0 + _dt.timedelta(seconds=i), hists))
    return out


def run_commit(num_metrics: int = 1024, bucket_limit: int = 512,
               reps: int = 8, tiers=((8, 1), (4, 8)),
               shapes: list[dict] | None = None) -> dict:
    """Fused-vs-fanout interval commit per mesh shape, interval-amortized.

    Every contender is fed the identical interval stream; latency is a
    host-blocking measure (block_until_ready on acc + every ring after
    each interval) so async dispatch cannot flatter either side.
    """
    import jax

    from bench import HBM_PEAK_BYTES_PER_S
    from loghisto_tpu.commit import IntervalCommitter
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.metrics import RawMetricSet
    from loghisto_tpu.parallel.aggregator import TPUAggregator
    from loghisto_tpu.parallel.mesh import make_mesh
    from loghisto_tpu.window import TimeWheel
    from loghisto_tpu.window import store as store_mod

    platform = jax.devices()[0].platform
    cap = HBM_PEAK_BYTES_PER_S.get(platform, 4e12)
    cfg = MetricConfig(bucket_limit=bucket_limit)
    rng = np.random.default_rng(0)
    stream = _commit_intervals(rng, reps + 2, num_metrics, bucket_limit)
    samples_per_interval = sum(
        sum(h.values()) for h in stream[2][1].values()
    )

    def raw_of(entry):
        t, hists = entry
        return RawMetricSet(time=t, counters={}, rates={},
                            histograms=hists, gauges={}, duration=1.0)

    def block(agg, wheel):
        agg._acc.block_until_ready()
        for t in wheel._tiers:
            t.ring.block_until_ready()

    def timed_fused(mesh):
        agg = TPUAggregator(num_metrics=num_metrics, config=cfg, mesh=mesh)
        wheel = TimeWheel(num_metrics=num_metrics, config=cfg, interval=1.0,
                          tiers=tiers, registry=agg.registry, mesh=mesh)
        committer = IntervalCommitter(agg, wheel)
        committer.warmup()
        committer.commit(raw_of(stream[0]))  # warm name resolution
        block(agg, wheel)
        times, dispatches = [], []
        for entry in stream[2:]:
            raw = raw_of(entry)
            t1 = time.perf_counter()
            committer.commit(raw)
            block(agg, wheel)
            times.append(time.perf_counter() - t1)
            dispatches.append(committer.last_dispatches)
        assert committer.fanout_intervals == 0
        return float(np.median(times)), int(np.median(dispatches))

    def timed_fanout(mesh):
        agg = TPUAggregator(num_metrics=num_metrics, config=cfg, mesh=mesh)
        wheel = TimeWheel(num_metrics=num_metrics, config=cfg, interval=1.0,
                          tiers=tiers, registry=agg.registry, mesh=mesh)
        agg._bridge_warmup()
        agg.merge_raw(raw_of(stream[0]))
        wheel.push(raw_of(stream[0]))
        block(agg, wheel)
        counts = {"n": 0}
        real_scatter = store_mod._scatter_cells_jit
        real_open = store_mod._open_slot_jit
        real_weighted = agg._weighted_ingest

        def counting(fn):
            def wrapped(*a, **kw):
                counts["n"] += 1
                return fn(*a, **kw)
            return wrapped

        store_mod._scatter_cells_jit = counting(real_scatter)
        store_mod._open_slot_jit = counting(real_open)
        agg._weighted_ingest = counting(real_weighted)
        times, dispatches = [], []
        try:
            for entry in stream[2:]:
                raw = raw_of(entry)
                counts["n"] = 0
                t1 = time.perf_counter()
                agg.merge_raw(raw)
                wheel.push(raw)
                block(agg, wheel)
                times.append(time.perf_counter() - t1)
                dispatches.append(counts["n"])
        finally:
            store_mod._scatter_cells_jit = real_scatter
            store_mod._open_slot_jit = real_open
            agg._weighted_ingest = real_weighted
        return float(np.median(times)), int(np.median(dispatches))

    n = len(jax.devices())
    if shapes is None:
        shapes = []
        metric = 1
        while metric <= n:
            if n % metric == 0 and num_metrics % metric == 0:
                shapes.append({"stream": n // metric, "metric": metric})
            metric *= 2

    result = {
        "metric": "mesh-sharded fused commit vs fan-out, per mesh shape",
        "platform": platform,
        # artifact-level flag mirroring the per-shape roofline guard:
        # on virtual CPU devices every absolute rate is suspect
        "suspect": platform != "tpu",
        "n_devices": n,
        "num_metrics": num_metrics,
        "num_buckets": cfg.num_buckets,
        "tiers": [list(t) for t in tiers],
        "reps": reps,
        "samples_per_interval": samples_per_interval,
        "hbm_peak_bytes_per_s": cap,
        "shapes": {},
    }

    def entry(fused, fanout, t_single_fused=None):
        fused_med, fused_disp = fused
        fan_med, fan_disp = fanout
        samples_per_s = samples_per_interval / max(fused_med, 1e-9)
        # roofline guard: every committed sample is at minimum one
        # int32 RMW (8 bytes); a rate above peak-bandwidth/8 means the
        # timing broke, not that the program is fast
        suspect = samples_per_s > cap / 8
        out = {
            "fused_commit_median_us": round(fused_med * 1e6, 1),
            "fanout_commit_median_us": round(fan_med * 1e6, 1),
            "fused_dispatches_per_interval": fused_disp,
            "fanout_dispatches_per_interval": fan_disp,
            "fused_samples_per_s": (
                None if suspect else round(samples_per_s, 1)
            ),
            "measured_samples_per_s": round(samples_per_s, 1),
            "suspect": suspect,
            "fanout_over_fused": (
                None if suspect
                else round(fan_med / max(fused_med, 1e-9), 2)
            ),
        }
        if suspect:
            print(
                f"mesh_scale: {samples_per_s:.3e} committed samples/s "
                f"exceeds the {platform} roofline cap {cap / 8:.3e}; "
                "withholding the headline for this shape",
                file=sys.stderr,
            )
        if t_single_fused is not None:
            out["fused_vs_single_device"] = round(
                fused_med / max(t_single_fused, 1e-9), 3
            )
        return out

    single_fused = timed_fused(None)
    result["shapes"]["single"] = entry(single_fused, timed_fanout(None))
    for shape in shapes:
        mesh = make_mesh(stream=shape["stream"], metric=shape["metric"])
        key = f"stream{shape['stream']}xmetric{shape['metric']}"
        result["shapes"][key] = entry(
            timed_fused(mesh), timed_fanout(mesh),
            t_single_fused=single_fused[0],
        )
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--metrics", type=int, default=10_000)
    parser.add_argument("--bucket-limit", type=int, default=4_096)
    parser.add_argument("--batch", type=int, default=1 << 22)
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument("--commit-only", action="store_true",
                        help="skip the distributed-step sweep and report "
                             "only the interval-commit contenders")
    parser.add_argument("--commit-metrics", type=int, default=1024)
    parser.add_argument("--commit-bucket-limit", type=int, default=512)
    parser.add_argument("--commit-reps", type=int, default=8)
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing virtual-CPU devices")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = {}
    if not args.commit_only:
        result = run(num_metrics=args.metrics,
                     bucket_limit=args.bucket_limit,
                     batch=args.batch, reps=args.reps)
    result["commit"] = run_commit(
        num_metrics=args.commit_metrics,
        bucket_limit=args.commit_bucket_limit,
        reps=args.commit_reps,
    )
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
