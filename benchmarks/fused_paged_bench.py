"""r17 direct-to-paged fused ingest characterization: the one-dispatch
compress -> log-bucket -> codec-encode -> page-translate -> scatter
route into the donated page pool vs the retired two-stage paged route
(host fold -> translate -> packed pool commit), the per-mesh-shape
roofline-fraction table, and the end-to-end interval budget (dispatches
per interval + staging-ring upload overlap) on the paged path.

Roofline-guarded like bench.py: samples/s above the platform's HBM-RMW
cap means the timing broke, so the headline is withheld with the raw
measurement left inspectable next to ``suspect: true``.  On CPU the
Pallas scatter tier runs in interpret mode — orders of magnitude slower
than compiled Mosaic — so CPU numbers calibrate the PIPELINE (dispatch
budget, overlap pct, route shape), not the kernel; the per-chip
roofline fraction only means something from a --tpu capture.

The mesh table is a RESOLUTION table, not a scaling sweep: since r18
the page pool shards across the ("stream","metric") mesh, so every
listed shape resolves ONTO the fused_paged route (the r17 rows showed
them declining off it; MESH_PAGED_r18.json has the sharded paged
scaling story, MESH_SCALE_r13 the sharded dense one).  A shape that
still declines — wrong axes, indivisible metric count — publishes the
capability table's own reason string instead of a fraction.

Usage: python benchmarks/fused_paged_bench.py [--metrics 4096]
       [--bucket-limit 512] [--batch 65536] [--reps 3] [--out FILE]
Prints one JSON object (save as FUSED_PAGED_r17.json); importable as
``run(...)`` / ``run_mesh_table(...)`` / ``run_interval_budget(...)``
for bench.py and tests.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

# ISSUE 17's published shape grid: single device plus the v5e-8 slices.
MESH_SHAPES = ("single", "8x1", "4x2", "2x4", "1x8")


class _MeshShape:
    """Just the surface the capability edges inspect — lets the
    resolution table cover 8-chip shapes without 8 devices."""

    def __init__(self, stream: int, metric: int):
        self.axis_names = ("stream", "metric")
        self.shape = {"stream": stream, "metric": metric}


def _store(num_metrics: int, bucket_limit: int, pool_pages: int):
    from loghisto_tpu.paging import PagedStore, PagedStoreConfig

    return PagedStore(
        num_metrics, bucket_limit,
        config=PagedStoreConfig(pool_pages=pool_pages, page_size=128),
    )


def _force(store) -> None:
    np.asarray(store._pool[:1, :1])


def run(num_metrics: int = 4_096, bucket_limit: int = 512,
        batch: int = 1 << 16, reps: int = 3,
        pool_pages: int = 8_192) -> dict:
    """Fused one-dispatch paged ingest vs the retired two-stage route
    (host fold -> translate -> packed commit) at one shape."""
    import jax
    import jax.numpy as jnp

    from bench import plausibility_cap_samples_per_s
    from loghisto_tpu import _native

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    ids = ((rng.zipf(1.3, batch) - 1) % num_metrics).astype(np.int32)
    values = rng.lognormal(6.0, 2.0, batch).astype(np.float32)

    # fused path: host prep (codec assignment + page allocation, the
    # work the bridge thread overlaps with device dispatch) happens
    # once per batch content; the timed loop is the ONE device dispatch
    st = _store(num_metrics, bucket_limit, pool_pages)
    t0 = time.perf_counter()
    prep_ids, _ = st.prepare_batch(ids, values)
    host_prep_s = time.perf_counter() - t0
    ids_dev = jnp.asarray(prep_ids)
    values_dev = jnp.asarray(values)
    st.ingest_raw(ids_dev, values_dev)  # compile + warm
    _force(st)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        st.ingest_raw(ids_dev, values_dev)
        _force(st)
        times.append(time.perf_counter() - t0)
    t_fused = float(np.median(times))
    pool_bytes = st.hbm_bytes()

    # two-stage route the fusion retires: numpy fold to (row, bucket,
    # count) triples, host translate through the page table, packed
    # pool commit (the r14 machinery, one extra dispatch + full host
    # fold per batch)
    st2 = _store(num_metrics, bucket_limit, pool_pages)

    def two_stage():
        buckets = _native.compress_np_host(values, st2.precision)
        keep = (ids >= 0) & (ids < num_metrics)
        keys = (ids[keep].astype(np.int64) << 16) | (
            buckets[keep].astype(np.int64) + 32768
        )
        uniq, counts = np.unique(keys, return_counts=True)
        packed = np.empty((len(uniq), 3), dtype=np.int32)
        packed[:, 0] = uniq >> 16
        packed[:, 1] = (uniq & 0xFFFF) - 32768
        packed[:, 2] = counts
        st2.commit(packed)
        _force(st2)

    two_stage()  # compile + warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        two_stage()
        times.append(time.perf_counter() - t0)
    t_two = float(np.median(times))

    cap = plausibility_cap_samples_per_s(platform, pool_bytes)
    sps = batch / t_fused
    suspect = sps > cap
    if suspect:
        print(
            f"fused_paged_bench: {sps:.3e} samples/s exceeds the "
            f"{platform} roofline cap {cap:.3e}; withholding headline",
            file=sys.stderr,
        )
    return {
        "metric": "direct-to-paged fused one-dispatch ingest vs retired "
                  "two-stage fold+translate+commit, samples/sec/chip",
        "platform": platform,
        "pallas_interpret": platform != "tpu",
        # artifact-level honesty flag: interpret-mode (non-TPU) numbers
        # characterize the pipeline shape, never the kernel — suspect
        # regardless of whether the roofline guard also tripped
        "suspect": bool(suspect or platform != "tpu"),
        "num_metrics": num_metrics,
        "num_buckets": 2 * bucket_limit + 1,
        "batch": batch,
        "reps": reps,
        "pool_hbm_bytes": pool_bytes,
        "roofline_cap_samples_per_s": cap,
        "fused": {
            "seconds_per_batch": round(t_fused, 4),
            "samples_per_s": None if suspect else round(sps, 1),
            "measured_samples_per_s": round(sps, 1),
            "roofline_fraction": round(min(sps / cap, 1.0), 4),
            "host_prep_seconds": round(host_prep_s, 4),
            "suspect": suspect,
        },
        "two_stage": {
            "seconds_per_batch": round(t_two, 4),
            "measured_samples_per_s": round(batch / t_two, 1),
        },
        "fused_over_two_stage": round(t_two / max(t_fused, 1e-9), 3),
    }


def run_mesh_table(num_metrics: int = 1 << 16, bucket_limit: int = 4_096,
                   batch: int = 1 << 20,
                   single_roofline_fraction: float | None = None) -> list:
    """Per-mesh-shape path resolution through resolve_full_path: which
    (transport, ingest, storage) route each shape actually takes, the
    capability reason when a shape declines the fused_paged route, and
    the measured single-device roofline fraction on the shape that runs
    it.  Resolution is pure table walking (no devices needed), which is
    the point: this documents WHAT runs where, with the same strings
    the explicit paths raise."""
    from loghisto_tpu.ops import dispatch

    rows = []
    for shape in MESH_SHAPES:
        if shape == "single":
            mesh = None
        else:
            stream, metric = (int(x) for x in shape.split("x"))
            mesh = _MeshShape(stream, metric)
        fp = dispatch.resolve_full_path(
            num_metrics, 2 * bucket_limit + 1, "tpu", batch_size=batch,
            mesh=mesh,
        )
        row = {
            "mesh": shape,
            "transport": fp.transport,
            "ingest": fp.ingest,
            "storage": fp.storage,
            "commit": fp.commit,
        }
        if fp.ingest == "fused_paged":
            # the measured fraction belongs to the shape it was measured
            # on; sharded shapes resolve the route (r18) but their
            # throughput story lives in MESH_PAGED_r18.json
            row["roofline_fraction"] = (
                single_roofline_fraction if shape == "single" else None
            )
        else:
            row["roofline_fraction"] = None
            row["declined"] = fp.reasons.get(
                "ingest:fused_paged", "fused_paged not resolved"
            )
        rows.append(row)
    return rows


def run_interval_budget(num_metrics: int = 4_096, bucket_limit: int = 512,
                        batch: int = 1 << 15, rounds: int = 2,
                        super_chunks_per_round: int = 4) -> dict:
    """End-to-end paged-path interval budget through the aggregator:
    device dispatches per interval (the acceptance bar is <= 2: the
    fused ingest dispatch, plus at most the interval's commit/readback)
    and the staging-ring upload/compute overlap — the r13 93% figure
    must survive composition with the paged pool (same ring, same
    span attribution, pool instead of dense accumulator)."""
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.obs.spans import SpanRecorder
    from loghisto_tpu.parallel.aggregator import TPUAggregator

    cfg = MetricConfig(bucket_limit=bucket_limit)
    agg = TPUAggregator(
        num_metrics=num_metrics, config=cfg, storage="paged",
        ingest_path="fused", batch_size=batch,
    )
    assert agg.fused_paged, agg.fused_paged_reason
    rec = SpanRecorder(capacity=8192)
    agg.obs_recorder = rec
    rng = np.random.default_rng(2)
    n = 8 * batch * super_chunks_per_round
    for _ in range(rounds):
        ids = rng.integers(0, num_metrics, n).astype(np.int32)
        values = rng.lognormal(6.0, 2.0, n).astype(np.float32)
        agg.record_batch(ids, values)
        agg.flush()
        agg.wait_transfers(timeout=300.0)
    fused_dispatches = agg.paged.fused_dispatches
    commits = agg.paged.commits
    batches = max(fused_dispatches, 1)
    uploads = [s for s in rec.spans() if s.stage == "ingest.upload"]
    dispatches = [s for s in rec.spans() if s.stage == "ingest.dispatch"]
    shipped, shed = agg._xfer_samples_shipped, agg._shed_samples
    agg.close()

    upload_ns = sum(s.end_ns - s.start_ns for s in uploads)
    hidden_ns = 0
    for u in uploads:
        for d in dispatches:
            lo = max(u.start_ns, d.start_ns)
            hi = min(u.end_ns, d.end_ns)
            if hi > lo:
                hidden_ns += hi - lo
    overlap_pct = 100.0 * hidden_ns / max(upload_ns, 1)
    import jax

    platform = jax.devices()[0].platform
    return {
        "metric": "paged-path interval budget + staging-ring overlap",
        "platform": platform,
        "suspect": platform != "tpu",
        "num_metrics": num_metrics,
        "batch": batch,
        "samples_shipped": shipped,
        "samples_shed": shed,
        "fused_dispatches": fused_dispatches,
        "pool_commits": commits,
        "dispatches_per_batch": round(
            (fused_dispatches + commits) / batches, 3
        ),
        "meets_two_dispatch_budget": (
            (fused_dispatches + commits) / batches <= 2.0
        ),
        "upload_spans": len(uploads),
        "dispatch_spans": len(dispatches),
        "upload_ms_total": round(upload_ns / 1e6, 2),
        "upload_ms_hidden": round(hidden_ns / 1e6, 2),
        "ingest_overlap_pct": round(min(overlap_pct, 100.0), 1),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--metrics", type=int, default=4_096)
    parser.add_argument("--bucket-limit", type=int, default=512)
    parser.add_argument("--batch", type=int, default=1 << 16)
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = run(num_metrics=args.metrics, bucket_limit=args.bucket_limit,
                 batch=args.batch, reps=args.reps)
    result["mesh_table"] = run_mesh_table(
        single_roofline_fraction=result["fused"]["roofline_fraction"]
        if not result["fused"]["suspect"] else None,
    )
    if args.tpu:
        result["interval_budget"] = run_interval_budget()
    else:
        # interpret-mode Pallas runs seconds per dispatch on one core;
        # the budget/overlap numbers are structural (dispatch counts,
        # span attribution), so a small population measures them fine
        result["interval_budget"] = run_interval_budget(
            num_metrics=1_024, batch=1 << 12, rounds=1,
            super_chunks_per_round=2,
        )
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
