"""Federation fan-in receipts (the ISSUE 11 tentpole): what many-emitter
ingest through the TCP tier actually sustains, and what a sample costs
on the wire.

Grid: 1 / 8 / 32 emitters x 1k / 10k metrics.  Each emitter is a
``FederationEmitter`` on its own thread (threads, not processes: the
wire path — fold, frame, TCP, decode, intern, merge — is identical, and
a 1-core CI box can't launch 32 interpreters without measuring mostly
exec overhead).  Emitters record uniform batches over the metric space,
fold+frame per batch, then pump their backlog through real loopback
sockets into one ``FederationReceiver`` draining into a real
``TPUAggregator``; the clock stops when every sample is merged AND the
aggregator's transfer queue is drained — fan-in samples/s is
end-to-end, not send-side.

``bytes_per_sample`` is receiver-side bytes over samples: the dictionary
delta amortizes to ~0 and each packed triple is 12 B covering however
many samples folded into its cell, so bigger batches/fewer distinct
cells => cheaper samples.

Roofline plausibility guard: fan-in samples/s times bytes/sample is the
implied loopback byte rate; a number above a generous loopback-bandwidth
ceiling (20 GB/s) is physically impossible for this topology and marks
the row suspect rather than reporting it.

Usage: python benchmarks/federation_bench.py [--samples 262144]
       [--out FEDERATION_r11.json]
Prints one JSON object (save as FEDERATION_r*.json); importable as
``run(...)`` for bench.py's ``federation_ingest_sps`` /
``federation_bytes_per_sample`` headline fields.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

BUCKET_LIMIT = 128
BATCH = 4096
LOOPBACK_PEAK_BYTES_PER_S = 2e10


def _cell(n_emitters: int, n_metrics: int, total_samples: int) -> dict:
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.federation.emitter import FederationEmitter
    from loghisto_tpu.federation.receiver import FederationReceiver
    from loghisto_tpu.parallel.aggregator import TPUAggregator

    cfg = MetricConfig(bucket_limit=BUCKET_LIMIT)
    agg = TPUAggregator(num_metrics=n_metrics + 16, config=cfg)
    rx = FederationReceiver(agg, recv_bytes=1 << 18)
    rx.start()

    batches_per_emitter = max(1, total_samples // (n_emitters * BATCH))
    per_emitter = batches_per_emitter * BATCH
    total = per_emitter * n_emitters

    def emit(idx: int, out: dict) -> None:
        e = FederationEmitter(
            ("127.0.0.1", rx.port), interval=3600.0, config=cfg,
            emitter_id=idx + 1,
            backlog_slots=batches_per_emitter + 8,
        )
        rng = np.random.default_rng(idx)
        # register the full name space up front (steady state: the
        # dictionary delta rides the first frame, then ~0 bytes)
        lids = np.array(
            [e.local_id(f"m{j}") for j in range(n_metrics)],
            dtype=np.int32,
        )
        for _ in range(batches_per_emitter):
            ids = lids[rng.integers(0, n_metrics, BATCH)]
            values = rng.lognormal(3.0, 2.0, BATCH).astype(np.float32)
            e.record_batch(ids, values)
            e.flush(heartbeat=False)  # one frame per batch
        ok = e.drain(timeout=600.0)  # pump the backlog through TCP
        out[idx] = (ok, e.samples_shipped, e.bytes_sent)

    results: dict = {}
    threads = [
        threading.Thread(target=emit, args=(i, results))
        for i in range(n_emitters)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    deadline = time.monotonic() + 600.0
    while rx.samples_merged < total and time.monotonic() < deadline:
        time.sleep(0.005)
    agg.wait_transfers()
    wall_s = time.perf_counter() - t0
    rx.stop()

    assert all(ok for ok, _, _ in results.values()), "emitter drain failed"
    assert rx.samples_merged == total, (rx.samples_merged, total)
    bytes_per_sample = rx.bytes_received / total
    sps = total / wall_s
    suspect = sps * bytes_per_sample > LOOPBACK_PEAK_BYTES_PER_S
    agg.close()
    return {
        "emitters": n_emitters,
        "metrics": n_metrics,
        "samples": total,
        "frames": rx.frames_received,
        "wall_s": round(wall_s, 3),
        "fanin_samples_per_s": round(sps, 1),
        "bytes_per_sample": round(bytes_per_sample, 3),
        "decode_errors": rx.decode_errors,
        "suspect": suspect,
    }


def run(
    emitter_counts=(1, 8, 32),
    metric_counts=(1_000, 10_000),
    samples_per_cell: int = 1 << 18,
) -> dict:
    grid = []
    for m in metric_counts:
        for e in emitter_counts:
            cell = _cell(e, m, samples_per_cell)
            grid.append(cell)
            print(
                f"federation_bench: {e:>2} emitters x {m:>6} metrics: "
                f"{cell['fanin_samples_per_s']:>12.0f} samples/s, "
                f"{cell['bytes_per_sample']:.2f} B/sample"
                + (" [SUSPECT]" if cell["suspect"] else ""),
                file=sys.stderr,
            )
    # the headline cell: the fleet shape the demo ships (8 emitters)
    # at the repo's standard 10k-metric working point
    head = next(
        (c for c in grid if c["emitters"] == 8 and c["metrics"] == 10_000),
        grid[-1],
    )
    return {
        "bench": "federation_fanin",
        "batch": BATCH,
        "bucket_limit": BUCKET_LIMIT,
        "grid": grid,
        "federation_ingest_sps": (
            None if head["suspect"] else head["fanin_samples_per_s"]
        ),
        "federation_bytes_per_sample": head["bytes_per_sample"],
        "suspect": head["suspect"],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--samples", type=int, default=1 << 18,
                        help="samples per grid cell")
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing CPU")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = run(samples_per_cell=args.samples)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
