"""Metric lifecycle under name churn (the lifecycle tentpole's
receipts): commit latency, eviction/compaction cost, and the bounded-
memory claim at 1k / 16k / 100k cumulative names on a fixed live-series
budget.

Every interval brings a fresh per-user name population
(``api.u<id>.lat``), the cardinality-explosion workload a dense device
accumulator cannot survive without retirement.  The lifecycle config
TTLs idle series, folds them (count-exact) into ``_overflow.api``, and
auto-compacts the freed rows, so the device row space must stay at its
configured budget while cumulative names grow unbounded — the run
ASSERTS sample conservation (nothing lost to eviction) and reports
whether the row space actually stayed bounded.

The HBM-roofline plausibility guard from bench.py marks any compaction
timing whose implied repack bandwidth (read + write of the accumulator
and every ring) exceeds the platform cap as suspect, rather than
reporting physically impossible latencies.

Usage: python benchmarks/cardinality_churn.py [--tpu]
       [--configs 1000,16000] [--out CARDINALITY_CHURN_r8.json]
Prints one JSON object (save as CARDINALITY_CHURN_r*.json); importable
as ``run(...)`` for tests/capture and for bench.py's headline extras.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

from bench import HBM_PEAK_BYTES_PER_S

# (label, cumulative_names, live_budget_rows, bucket_limit, tiers)
# The big points shrink buckets and tier depth so the rings fit
# everywhere; the contest is churn handling, not ring HBM.  The 100k
# point is the acceptance grid: 100k cumulative names on a 16k live
# budget.
CONFIGS = [
    ("1000", 1_000, 256, 1024, ((8, 1), (4, 8))),
    ("16000", 16_000, 2_048, 256, ((8, 1), (4, 8))),
    ("100000", 100_000, 16_384, 64, ((4, 1),)),
]

INTERVALS = 40


def _stats_us(lat_s):
    return {
        "median_us": round(float(np.median(lat_s)) * 1e6, 1),
        "p99_us": round(float(np.percentile(lat_s, 99)) * 1e6, 1),
    }


def run(configs=None) -> dict:
    import jax

    from loghisto_tpu.commit import IntervalCommitter
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.lifecycle import LifecycleConfig, LifecycleManager
    from loghisto_tpu.metrics import RawMetricSet
    from loghisto_tpu.parallel.aggregator import TPUAggregator
    from loghisto_tpu.window import TimeWheel

    platform = jax.devices()[0].platform
    cap = HBM_PEAK_BYTES_PER_S.get(platform, 4e12)
    wanted = set(configs) if configs else None
    result = {
        "metric": "interval commit + lifecycle cost under name churn",
        "platform": platform,
        "intervals": INTERVALS,
        "hbm_peak_bytes_per_s": cap,
        "configs": {},
    }
    for label, cumulative, rows, bucket_limit, tiers in CONFIGS:
        if wanted is not None and label not in wanted:
            continue
        churn = cumulative // INTERVALS
        cfg = MetricConfig(bucket_limit=bucket_limit)
        agg = TPUAggregator(num_metrics=rows, config=cfg)
        wheel = TimeWheel(num_metrics=rows, config=cfg, interval=1.0,
                          tiers=tiers, registry=agg.registry)
        # auto-compaction off: the repack is driven explicitly every 4
        # intervals below so every grid point yields compaction timings
        # (the auto trigger calls the same compact() path)
        lc = LifecycleManager(agg, wheel, LifecycleConfig(
            ttl_intervals=2,
            check_every=1,
            auto_compact_fragmentation=0.0,
        ))
        committer = IntervalCommitter(agg, wheel, lifecycle=lc)
        committer.warmup()

        rng = np.random.default_rng(0)
        t0 = _dt.datetime(2026, 1, 1, tzinfo=_dt.timezone.utc)
        total = 0
        peak_rows = agg.num_metrics
        commit_lat = []
        uid = 0
        for i in range(INTERVALS):
            hists = {}
            buckets = rng.integers(-bucket_limit, bucket_limit, churn)
            counts = rng.integers(1, 8, churn)
            for b, c in zip(buckets, counts):
                hists[f"api.u{uid}.lat"] = {int(b): int(c)}
                total += int(c)
                uid += 1
            hists["api.steady"] = {0: 10}
            total += 10
            raw = RawMetricSet(
                time=t0 + _dt.timedelta(seconds=i), counters={},
                rates={}, histograms=hists, gauges={}, duration=1.0,
            )
            t1 = time.perf_counter()
            committer.commit(raw)
            jax.block_until_ready(agg._acc)
            commit_lat.append(time.perf_counter() - t1)
            peak_rows = max(peak_rows, agg.num_metrics)
            if (i + 1) % 4 == 0:
                lc.compact()  # records its latency in lc._compaction_us

        # lossless retirement: every committed sample is still on device,
        # either in a live row or folded into the overflow row
        acc = np.asarray(
            agg._finalize_acc(agg._acc), dtype=np.int64
        )
        if agg._spill is not None:
            acc = acc + agg._spill
        assert int(acc.sum()) == total, (
            f"conservation broken: committed {total}, device holds "
            f"{int(acc.sum())}"
        )
        ovid = agg.registry.lookup("_overflow.api")
        overflow_count = int(acc[ovid].sum()) if ovid is not None else 0
        assert overflow_count == lc.overflowed_samples

        # bounded memory: the row space must never have grown past the
        # configured live budget — that IS the tentpole's claim
        bounded = peak_rows == rows
        hbm_bytes = (
            peak_rows * cfg.num_buckets * 4          # accumulator
            + wheel.hbm_bytes()                      # tier rings
            + peak_rows * 4                          # activity vector
        )

        comp_us = np.asarray(lc._compaction_us, dtype=np.float64)
        # plausibility: a repack reads + writes the accumulator and every
        # ring once; faster than the roofline means broken timing
        repack_bytes = 2 * (
            peak_rows * cfg.num_buckets * 4 + wheel.hbm_bytes()
        )
        suspect = False
        if len(comp_us):
            implied_bw = repack_bytes / max(
                float(np.median(comp_us)) / 1e6, 1e-9
            )
            suspect = implied_bw > cap
            if suspect:
                print(
                    f"cardinality_churn: implied compaction bandwidth "
                    f"{implied_bw:.3e} B/s exceeds the {platform} roofline"
                    f" cap {cap:.3e}; marking config {label} suspect",
                    file=sys.stderr,
                )
        result["configs"][label] = {
            "cumulative_names": cumulative,
            "live_budget_rows": rows,
            "churn_names_per_interval": churn,
            "num_buckets": cfg.num_buckets,
            "tiers": [list(t_) for t_ in tiers],
            "peak_device_rows": peak_rows,
            "bounded_by_live_budget": bounded,
            "peak_hbm_bytes": hbm_bytes,
            "live_series_final": agg.registry.live_count(),
            "evicted_series": lc.evicted_series,
            "eviction_batches": lc.evictions,
            "overflowed_samples": lc.overflowed_samples,
            "samples_committed": total,
            "compactions": lc.compactions,
            "commit_latency": _stats_us(commit_lat),
            "compaction_latency": (
                _stats_us(comp_us / 1e6) if len(comp_us) else None
            ),
            "repack_bytes_per_compaction": repack_bytes,
            "suspect": suspect,
        }
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing CPU")
    parser.add_argument("--configs", default=None,
                        help="comma-separated config labels (default all)")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    configs = args.configs.split(",") if args.configs else None
    result = run(configs=configs)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
