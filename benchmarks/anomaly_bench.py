"""Drift-engine receipts (the ISSUE 7 tentpole): what the EWMA baseline
bank and the fused divergence pass actually cost, at 1 / 16 / 10k metric
rows.

Three contenders over identical interval streams:

  * baseline — the fused IntervalCommitter as shipped by the commit
    tentpole (no drift engine);
  * ewma     — AnomalyManager attached with scoring disabled
    (``check_every`` huge): the EWMA bank update rides the final-chunk
    donated program (``track_baseline``) at ZERO extra dispatches —
    this delta is the pure ride-along cost;
  * drift    — the full engine: EWMA ride-along plus ONE divergence
    dispatch per interval (KS + JSD + bucket EMD against the baseline
    bank).

Reported per config: commit latency for all three contenders (the EWMA
rides existing dispatches, so its delta is the fused program doing more
work, not more launches — the dispatch counters are asserted, not
trusted), the divergence-pass latency, and the scoring cost per row.

The HBM-roofline plausibility guard from bench.py marks any divergence
timing whose implied operand bandwidth (live CDFs + baseline bank in)
exceeds the platform cap as suspect rather than reporting a
faster-than-physics number.

Usage: python benchmarks/anomaly_bench.py [--reps 20] [--tpu]
       [--out ANOMALY_r9.json]
Prints one JSON object (save as ANOMALY_r*.json); importable as
``run(...)`` for tests/capture and for bench.py's ``drift_*`` headline
fields.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

from bench import HBM_PEAK_BYTES_PER_S

# (label, num_metrics, bucket_limit, tiers) — the query-engine grid: the
# 10k point shrinks buckets/tier depth so the rings fit everywhere; the
# contest here is the EWMA ride-along and the divergence dispatch.
CONFIGS = [
    ("1", 1, 4096, ((60, 1), (60, 60), (24, 3600))),
    ("16", 16, 4096, ((60, 1), (60, 60), (24, 3600))),
    ("10000", 10_000, 256, ((8, 1), (4, 8))),
]

WARM_INTERVALS = 4  # committed before any timing starts
BANKS = 2           # exercise the bank gather, not just bank 0


def _intervals(rng, n, num_metrics, bucket_limit, cells_per_metric=8):
    t0 = _dt.datetime(2026, 1, 1, tzinfo=_dt.timezone.utc)
    names = [f"m{i}" for i in range(num_metrics)]
    out = []
    for i in range(n):
        hists = {}
        for name in names:
            b = rng.integers(-bucket_limit, bucket_limit, cells_per_metric)
            c = rng.integers(1, 100, cells_per_metric)
            h = {}
            for bb, cc in zip(b, c):
                h[int(bb)] = h.get(int(bb), 0) + int(cc)
            hists[name] = h
        out.append((t0 + _dt.timedelta(seconds=i), hists))
    return out


def _stats_us(lat):
    return {
        "median_us": round(float(np.median(lat)) * 1e6, 1),
        "p99_us": round(float(np.percentile(lat, 99)) * 1e6, 1),
    }


def run(reps: int = 20, configs=None) -> dict:
    import jax

    from loghisto_tpu.anomaly import AnomalyConfig, AnomalyManager
    from loghisto_tpu.commit import IntervalCommitter
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.metrics import RawMetricSet
    from loghisto_tpu.parallel.aggregator import TPUAggregator
    from loghisto_tpu.window import TimeWheel

    platform = jax.devices()[0].platform
    cap = HBM_PEAK_BYTES_PER_S.get(platform, 4e12)
    result = {
        "metric": "drift-engine cost: EWMA ride-along + divergence dispatch",
        "platform": platform,
        "reps": reps,
        "banks": BANKS,
        "hbm_peak_bytes_per_s": cap,
        "configs": {},
    }
    for label, num_metrics, bucket_limit, tiers in CONFIGS:
        if configs is not None and label not in configs:
            continue
        cfg = MetricConfig(bucket_limit=bucket_limit)
        rng = np.random.default_rng(0)
        stream = _intervals(rng, WARM_INTERVALS + reps, num_metrics,
                            bucket_limit)

        def raw_of(entry):
            t, hists = entry
            return RawMetricSet(time=t, counters={}, rates={},
                                histograms=hists, gauges={}, duration=1.0)

        def build(with_drift, check_every=1):
            agg = TPUAggregator(num_metrics=num_metrics, config=cfg)
            wheel = TimeWheel(num_metrics=num_metrics, config=cfg,
                              interval=1.0, tiers=tiers,
                              registry=agg.registry)
            am = None
            if with_drift:
                am = AnomalyManager(agg, wheel, AnomalyConfig(
                    banks=BANKS, bank_of=lambda t: t.second,
                    decay=0.95, min_samples=8,
                    check_every=check_every,
                ))
            com = IntervalCommitter(agg, wheel, anomaly=am)
            com.warmup()
            return com, agg, am

        def commit_lat(com, am):
            lat = []
            for k, entry in enumerate(stream):
                raw = raw_of(entry)
                if k < WARM_INTERVALS:
                    com.commit(raw)
                    continue
                t1 = time.perf_counter()
                com.commit(raw)
                lat.append(time.perf_counter() - t1)
                # the guarantee is structural, assert it every interval:
                # EWMA rides the commit (<= 2 launches), scoring adds 1
                assert com.last_dispatches <= 2
            return lat

        base_com, base_agg, _ = build(with_drift=False)
        base_lat = commit_lat(base_com, None)
        base_agg._acc.block_until_ready()

        # scoring disabled: the commit delta is the EWMA ride-along alone
        ewma_com, ewma_agg, ewma_am = build(with_drift=True,
                                            check_every=1 << 30)
        ewma_lat = commit_lat(ewma_com, ewma_am)
        ewma_agg._acc.block_until_ready()
        assert ewma_am.scored_intervals == 0

        com, agg, am = build(with_drift=True)
        drift_lat = commit_lat(com, am)
        agg._acc.block_until_ready()
        assert am.scored_intervals == WARM_INTERVALS + reps
        assert am.skipped_intervals == 0

        # the divergence pass in isolation (score_now = ONE dispatch +
        # host readback of 3*M floats; this is the engine's entire
        # per-interval device cost beyond the commit)
        now = stream[-1][0]
        score_lat = []
        for _ in range(reps):
            t1 = time.perf_counter()
            am.score_now(now)
            score_lat.append(time.perf_counter() - t1)

        score_med = float(np.median(score_lat))
        # plausibility: operands in (live view CDF + counts + the FULL
        # bank carries the gather reads) bound the pass from below
        b = cfg.num_buckets
        op_bytes = (
            num_metrics * b * 4        # view cdf  int32 [M, B]
            + num_metrics * 4          # counts    int32 [M]
            + BANKS * num_metrics * b * 4  # prof  f32 [K, M, B]
            + BANKS * num_metrics * 4      # wsum  f32 [K, M]
        )
        implied_bw = op_bytes / max(score_med, 1e-9)
        suspect = implied_bw > cap
        if suspect:
            print(
                f"anomaly_bench: implied divergence bandwidth "
                f"{implied_bw:.3e} B/s exceeds the {platform} roofline "
                f"cap {cap:.3e}; marking config {label} suspect",
                file=sys.stderr,
            )

        base_med = float(np.median(base_lat))
        ewma_med = float(np.median(ewma_lat))
        drift_med = float(np.median(drift_lat))
        result["configs"][label] = {
            "num_metrics": num_metrics,
            "num_buckets": b,
            "tiers": [list(t_) for t_ in tiers],
            "divergence_path": am.divergence_path,
            "commit_baseline": _stats_us(base_lat),
            "commit_ewma_only": _stats_us(ewma_lat),
            "commit_with_drift": _stats_us(drift_lat),
            "ewma_overhead_pct": round(
                (ewma_med / max(base_med, 1e-9) - 1.0) * 100.0, 1
            ),
            "commit_overhead_pct": round(
                (drift_med / max(base_med, 1e-9) - 1.0) * 100.0, 1
            ),
            "ewma_extra_dispatches": 0,  # asserted via last_dispatches
            "divergence_dispatches_per_interval": 1,
            "divergence_score": _stats_us(score_lat),
            "divergence_ns_per_row": round(
                score_med * 1e9 / num_metrics, 1
            ),
            "divergence_operand_bytes": op_bytes,
            "implied_divergence_bytes_per_s": round(implied_bw, 1),
            "suspect": suspect,
        }
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reps", type=int, default=20)
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing CPU")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = run(reps=args.reps)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
