"""Host-fed ingest benchmark (VERDICT r1 item 4): sustained samples/s
through the FULL host->device path — record_batch staging, the depth-K
ingest staging ring's async device_puts, device-side chunk slicing,
fused compress+scatter-add — unlike the firehose bench, whose samples
are generated on device and never cross PCIe/host memory.

r6 adds the transport dimension: --transport sparse ships flush-time
host-folded packed triples, --sweep measures raw/preagg/sparse in one
process and emits a comparison table (--out H2D_r6.json).  Every line
carries bytes/sample and effective wire MB/s from the aggregator's
transfer counters, and the samples/s figure is withheld (suspect=true)
when it exceeds the same HBM-roofline cap bench.py's headline uses.

Usage: python benchmarks/h2d_bench.py [--metrics 10000] [--seconds 5]
       [--batch 1048576] [--transport raw|preagg|sparse|auto]
       [--sweep] [--out H2D_r6.json] [--cpu]
Prints one JSON line (or one per transport plus a summary with --sweep).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


def run(num_metrics: int, seconds: float, batch: int,
        transport: str = "auto") -> dict:
    import jax

    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.parallel.aggregator import TPUAggregator

    cfg = MetricConfig(bucket_limit=4096)
    agg = TPUAggregator(
        num_metrics=num_metrics,
        config=cfg,
        batch_size=batch,
        max_metrics=num_metrics,
        transport=transport,
    )
    rng = np.random.default_rng(0)
    # pre-generate a pool of host batches (shuffled reuse; generation must
    # not gate the measured path)
    pool = []
    for _ in range(8):
        raw = rng.zipf(1.3, size=batch)
        ids = ((raw - 1) % num_metrics).astype(np.int32)
        values = rng.lognormal(10.0, 2.0, batch).astype(np.float32)
        pool.append((ids, values))

    import jax.numpy as jnp

    def force_value():
        # a host VALUE fetch, not block_until_ready: an asynchronous
        # tunnel backend can report readiness before execution finished.
        # Per-row device reduce (int32-safe: one interval's whole acc
        # holds < 2^31 samples by the spill guarantee), then an exact
        # int64 total on host; the wire carries one [M] vector.
        row_sums = np.asarray(
            jnp.sum(agg._finalize_acc(agg._acc), axis=1)
        )
        return int(row_sums.astype(np.int64).sum())

    # warmup: one full flush compiles the ingest executable
    agg.record_batch(*pool[0])
    agg.flush(force=True)
    warm_count = force_value()
    warm_stats = agg.transport_stats()

    sent = 0
    t0 = time.perf_counter()
    i = 0
    while time.perf_counter() - t0 < seconds:
        # backpressure pacing: a producer that overruns the bounded
        # buffer measures the shed machinery (and, on small hosts,
        # starves the transfer worker of the very cores it needs) —
        # sustained throughput is the worker's drain rate with the
        # queue kept full, so yield while it's saturated
        if agg._xfer_queued_samples >= agg.max_pending_samples:
            time.sleep(0.0005)
            continue
        ids, values = pool[i % len(pool)]
        agg.record_batch(ids, values)  # auto-flushes at batch_size
        sent += len(ids)
        i += 1
    agg.flush(force=True)
    delivered_device = int(force_value())
    elapsed = time.perf_counter() - t0
    # sustained = samples that actually REACHED the accumulator; counting
    # shed samples would overstate throughput whenever the bounded host
    # buffer dropped under device cooldown
    delivered = sent - agg._shed_samples
    spilled = int(agg._spill.sum()) if agg._spill is not None else 0
    stats = agg.transport_stats()
    # warmup-batch traffic subtracted: the wire economics of the measured
    # window only
    wire_bytes = stats["bytes_uploaded"] - warm_stats["bytes_uploaded"]
    shipped = stats["samples_shipped"] - warm_stats["samples_shipped"]
    rate = delivered / elapsed

    from bench import plausibility_cap_samples_per_s

    cfg_bytes = num_metrics * cfg.num_buckets * 4
    platform = jax.devices()[0].platform
    cap = plausibility_cap_samples_per_s(platform, cfg_bytes)
    suspect = rate > cap
    out = {
        "metric": "host-fed samples/sec/chip",
        # same contract as bench.py's headline: a physically impossible
        # rate is withheld, never laundered into a result line
        "value": None if suspect else round(rate, 1),
        "suspect": suspect,
        "measured_samples_per_s": round(rate, 1),
        "plausibility_cap_samples_per_s": round(cap, 1),
        "unit": "samples/s",
        "platform": platform,
        "transport": agg.transport,
        "probe_density": stats["probe_density"],
        # wire economics: what one delivered sample cost on the H2D link
        "bytes_per_sample": (
            round(wire_bytes / shipped, 3) if shipped else None
        ),
        "wire_mb_per_s": round(wire_bytes / elapsed / 1e6, 1),
        "num_metrics": num_metrics,
        "batch": batch,
        "seconds": round(elapsed, 2),
        "shed": agg._shed_samples,
        # device-side count: cross-checks that `delivered` samples truly
        # landed in the accumulator (+ any exact host spill; warmup
        # batch subtracted)
        "device_count": delivered_device + spilled - warm_count,
    }
    agg.close()
    return out


def sweep(num_metrics: int, seconds: float, batch: int) -> dict:
    """Measure every concrete transport on the identical load and report
    the comparison the auto-dispatch crossover is tuned from.  Each
    transport gets its own aggregator (fresh accumulator, fresh compile
    cache entry); the winner is picked on delivered samples/s among
    non-suspect lines."""
    table = {}
    for transport in ("raw", "preagg", "sparse"):
        table[transport] = run(
            num_metrics, seconds, batch, transport=transport
        )
    best = max(
        (t for t in table if not table[t]["suspect"]),
        key=lambda t: table[t]["measured_samples_per_s"],
        default=None,
    )
    return {
        "metric": "h2d transport sweep",
        "best_transport": best,
        "best_samples_per_s": (
            table[best]["measured_samples_per_s"] if best else None
        ),
        "num_metrics": num_metrics,
        "batch": batch,
        "seconds_per_transport": seconds,
        "transports": table,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--metrics", type=int, default=10_000)
    parser.add_argument("--seconds", type=float, default=5.0)
    parser.add_argument("--batch", type=int, default=1 << 20)
    parser.add_argument("--transport", default="auto",
                        choices=("auto", "raw", "preagg", "sparse"))
    parser.add_argument("--sweep", action="store_true",
                        help="measure raw, preagg AND sparse; print the "
                             "comparison table")
    parser.add_argument("--out", default=None,
                        help="also write the result JSON to this path "
                             "(e.g. benchmarks/H2D_r6.json)")
    parser.add_argument("--cpu", action="store_true")
    args = parser.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.sweep:
        result = sweep(args.metrics, args.seconds, args.batch)
    else:
        result = run(args.metrics, args.seconds, args.batch,
                     transport=args.transport)
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")


if __name__ == "__main__":
    main()
