"""Host-fed ingest benchmark (VERDICT r1 item 4): sustained samples/s
through the FULL host->device path — record_batch staging, one async
device_put per 8-batch super-chunk, device-side chunk slicing, fused
compress+scatter-add — unlike the firehose bench, whose samples are
generated on device and never cross PCIe/host memory.

Usage: python benchmarks/h2d_bench.py [--metrics 10000] [--seconds 5]
       [--batch 1048576] [--cpu]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


def run(num_metrics: int, seconds: float, batch: int,
        transport: str = "auto") -> dict:
    import jax

    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.parallel.aggregator import TPUAggregator

    cfg = MetricConfig(bucket_limit=4096)
    agg = TPUAggregator(
        num_metrics=num_metrics,
        config=cfg,
        batch_size=batch,
        max_metrics=num_metrics,
        transport=transport,
    )
    rng = np.random.default_rng(0)
    # pre-generate a pool of host batches (shuffled reuse; generation must
    # not gate the measured path)
    pool = []
    for _ in range(8):
        raw = rng.zipf(1.3, size=batch)
        ids = ((raw - 1) % num_metrics).astype(np.int32)
        values = rng.lognormal(10.0, 2.0, batch).astype(np.float32)
        pool.append((ids, values))

    import jax.numpy as jnp

    def force_value():
        # a host VALUE fetch, not block_until_ready: an asynchronous
        # tunnel backend can report readiness before execution finished.
        # Per-row device reduce (int32-safe: one interval's whole acc
        # holds < 2^31 samples by the spill guarantee), then an exact
        # int64 total on host; the wire carries one [M] vector.
        row_sums = np.asarray(
            jnp.sum(agg._finalize_acc(agg._acc), axis=1)
        )
        return int(row_sums.astype(np.int64).sum())

    # warmup: one full flush compiles the ingest executable
    agg.record_batch(*pool[0])
    agg.flush(force=True)
    warm_count = force_value()

    sent = 0
    t0 = time.perf_counter()
    i = 0
    while time.perf_counter() - t0 < seconds:
        ids, values = pool[i % len(pool)]
        agg.record_batch(ids, values)  # auto-flushes at batch_size
        sent += len(ids)
        i += 1
    agg.flush(force=True)
    delivered_device = int(force_value())
    elapsed = time.perf_counter() - t0
    # sustained = samples that actually REACHED the accumulator; counting
    # shed samples would overstate throughput whenever the bounded host
    # buffer dropped under device cooldown
    delivered = sent - agg._shed_samples
    spilled = int(agg._spill.sum()) if agg._spill is not None else 0
    return {
        "metric": "host-fed samples/sec/chip",
        "value": round(delivered / elapsed, 1),
        "unit": "samples/s",
        "platform": jax.devices()[0].platform,
        "transport": agg.transport,
        "num_metrics": num_metrics,
        "batch": batch,
        "seconds": round(elapsed, 2),
        "shed": agg._shed_samples,
        # device-side count: cross-checks that `delivered` samples truly
        # landed in the accumulator (+ any exact host spill; warmup
        # batch subtracted)
        "device_count": delivered_device + spilled - warm_count,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--metrics", type=int, default=10_000)
    parser.add_argument("--seconds", type=float, default=5.0)
    parser.add_argument("--batch", type=int, default=1 << 20)
    parser.add_argument("--transport", default="auto",
                        choices=("auto", "raw", "preagg"))
    parser.add_argument("--cpu", action="store_true")
    args = parser.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(run(args.metrics, args.seconds, args.batch,
                         transport=args.transport)))


if __name__ == "__main__":
    main()
