"""Fused vs fan-out interval-commit latency (the tentpole's receipts):
dispatches/interval, H2D bytes/interval, and per-interval commit
latency for both pipelines at 1 / 16 / 10k metric cardinalities.

The fan-out contender is the pre-existing pair of consumers fed the
same interval — TPUAggregator.merge_raw (bridge-merge scatter) plus
TimeWheel.push (one scatter per tier, plus slot clears) — each
re-resolving names and re-uploading cells.  The fused contender is
loghisto_tpu.commit.IntervalCommitter: one staged upload, one
donated-carry program for every consumer.

Commit latency is a host-blocking measure (block_until_ready on the
carries after each interval) so async dispatch cannot flatter either
side; the HBM-roofline plausibility guard from bench.py additionally
marks any implied cell bandwidth above the platform cap as suspect
rather than reporting it.

Usage: python benchmarks/interval_commit.py [--reps 30] [--tpu]
       [--out INTERVAL_COMMIT_r1.json]
Prints one JSON object (save as INTERVAL_COMMIT_r*.json); importable as
``run(...)`` for tests/capture.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

from bench import HBM_PEAK_BYTES_PER_S

# (label, num_metrics, bucket_limit, tiers): the 10k point shrinks the
# bucket space and tier depth so the rings fit comfortably everywhere —
# the contest is dispatch count and upload traffic, not ring HBM.
CONFIGS = [
    ("1", 1, 4096, ((60, 1), (60, 60), (24, 3600))),
    ("16", 16, 4096, ((60, 1), (60, 60), (24, 3600))),
    ("10000", 10_000, 256, ((8, 1), (4, 8))),
]


def _intervals(rng, n, num_metrics, bucket_limit, cells_per_metric=24):
    """Pre-built sparse interval payloads ({name: {bucket: count}}) —
    identical streams for both contenders."""
    t0 = _dt.datetime(2026, 1, 1, tzinfo=_dt.timezone.utc)
    names = [f"m{i}" for i in range(num_metrics)]
    out = []
    for i in range(n):
        hists = {}
        for name in names:
            b = rng.integers(-bucket_limit, bucket_limit, cells_per_metric)
            # weights sized so a full run stays inside the spill
            # threshold without a mid-run collect() reset (live traffic
            # gets that reset every collection interval)
            c = rng.integers(1, 100, cells_per_metric)
            h = {}
            for bb, cc in zip(b, c):
                h[int(bb)] = h.get(int(bb), 0) + int(cc)
            hists[name] = h
        out.append((t0 + _dt.timedelta(seconds=i), hists))
    return out


def _block(agg, wheel):
    agg._acc.block_until_ready()
    for t in wheel._tiers:
        t.ring.block_until_ready()


def run(reps: int = 30) -> dict:
    import jax

    from loghisto_tpu.commit import IntervalCommitter
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.metrics import RawMetricSet
    from loghisto_tpu.parallel.aggregator import TPUAggregator
    from loghisto_tpu.window import TimeWheel
    from loghisto_tpu.window import store as store_mod

    platform = jax.devices()[0].platform
    result = {
        "metric": "interval commit latency, fused vs fan-out",
        "platform": platform,
        "reps": reps,
        "hbm_peak_bytes_per_s": HBM_PEAK_BYTES_PER_S.get(platform, 4e12),
        "configs": {},
    }
    for label, num_metrics, bucket_limit, tiers in CONFIGS:
        cfg = MetricConfig(bucket_limit=bucket_limit)
        rng = np.random.default_rng(0)
        stream = _intervals(rng, reps + 2, num_metrics, bucket_limit)

        def raw_of(entry):
            t, hists = entry
            return RawMetricSet(time=t, counters={}, rates={},
                                histograms=hists, gauges={}, duration=1.0)

        # -- fused ------------------------------------------------------ #
        agg = TPUAggregator(num_metrics=num_metrics, config=cfg)
        wheel = TimeWheel(num_metrics=num_metrics, config=cfg, interval=1.0,
                          tiers=tiers, registry=agg.registry)
        committer = IntervalCommitter(agg, wheel)
        committer.warmup()
        committer.commit(raw_of(stream[0]))  # warm name resolution
        _block(agg, wheel)
        fused_times, fused_dispatches, fused_bytes = [], [], []
        for entry in stream[2:]:
            raw = raw_of(entry)
            t1 = time.perf_counter()
            committer.commit(raw)
            _block(agg, wheel)
            fused_times.append(time.perf_counter() - t1)
            fused_dispatches.append(committer.last_dispatches)
            fused_bytes.append(committer.last_h2d_bytes)
        assert committer.fanout_intervals == 0

        # -- fan-out (the pre-existing per-consumer pipelines) ---------- #
        agg2 = TPUAggregator(num_metrics=num_metrics, config=cfg)
        wheel2 = TimeWheel(num_metrics=num_metrics, config=cfg, interval=1.0,
                           tiers=tiers, registry=agg2.registry)
        agg2._bridge_warmup()
        agg2.merge_raw(raw_of(stream[0]))
        wheel2.push(raw_of(stream[0]))
        _block(agg2, wheel2)

        # count the fan-out's device launches the same way the guard test
        # counts the fused path's: wrap the jitted entry points
        counts = {"n": 0}
        real_scatter = store_mod._scatter_cells_jit
        real_open = store_mod._open_slot_jit
        real_weighted = agg2._weighted_ingest

        def counting(fn):
            def wrapped(*a, **kw):
                counts["n"] += 1
                return fn(*a, **kw)
            return wrapped

        store_mod._scatter_cells_jit = counting(real_scatter)
        store_mod._open_slot_jit = counting(real_open)
        agg2._weighted_ingest = counting(real_weighted)
        fan_times, fan_dispatches = [], []
        try:
            for entry in stream[2:]:
                raw = raw_of(entry)
                counts["n"] = 0
                t1 = time.perf_counter()
                agg2.merge_raw(raw)
                wheel2.push(raw)
                _block(agg2, wheel2)
                fan_times.append(time.perf_counter() - t1)
                fan_dispatches.append(counts["n"])
        finally:
            store_mod._scatter_cells_jit = real_scatter
            store_mod._open_slot_jit = real_open
            agg2._weighted_ingest = real_weighted

        fused_med = float(np.median(fused_times))
        fan_med = float(np.median(fan_times))
        h2d_per_interval = int(np.median(fused_bytes))
        # plausibility: implied H2D bandwidth for the fused upload must
        # stay under the platform roofline, else the timing is broken
        implied_bw = h2d_per_interval / max(fused_med, 1e-9)
        cap = HBM_PEAK_BYTES_PER_S.get(platform, 4e12)
        suspect = implied_bw > cap
        if suspect:
            print(
                f"interval_commit: implied H2D {implied_bw:.3e} B/s exceeds "
                f"the {platform} roofline cap {cap:.3e}; withholding the "
                "speedup headline for this config", file=sys.stderr,
            )
        result["configs"][label] = {
            "num_metrics": num_metrics,
            "num_buckets": cfg.num_buckets,
            "tiers": [list(t) for t in tiers],
            "fused_commit_median_us": round(fused_med * 1e6, 1),
            "fanout_commit_median_us": round(fan_med * 1e6, 1),
            "fused_dispatches_per_interval": int(np.median(fused_dispatches)),
            "fanout_dispatches_per_interval": int(np.median(fan_dispatches)),
            "fused_h2d_bytes_per_interval": h2d_per_interval,
            "implied_h2d_bytes_per_s": round(implied_bw, 1),
            "suspect": suspect,
            "fanout_over_fused": (
                None if suspect else round(fan_med / max(fused_med, 1e-9), 2)
            ),
        }
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reps", type=int, default=30)
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing CPU")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = run(reps=args.reps)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
