"""Snapshot query engine vs locked recompute (the query tentpole's
receipts): percentile-query latency at 1 / 16 / 10k metric
cardinalities, full-glob vs single-metric, warm-cached vs
fresh-dispatch, against the pre-change recompute baseline.

The baseline contender is a ``snapshots=False`` TimeWheel — queries
take the store lock and run the full masked merge + dense_stats over
every ring row (the pre-snapshot path, kept in-tree as
``_query_recompute``).  The snapshot contender is the same stream
committed through the fused IntervalCommitter, which publishes a
per-tier CDF snapshot at commit time; queries then cost one sparse
gather+searchsorted dispatch over only the matched rows
(fresh-dispatch), or zero dispatch when the epoch hasn't advanced
(warm-cached).

Latency is host-blocking end-to-end (WindowStats is host-side numpy,
so readback is inside the clock).  The HBM-roofline plausibility guard
from bench.py marks any recompute timing whose implied ring bandwidth
exceeds the platform cap as suspect rather than reporting a speedup
derived from broken timing.

The single-metric leg additionally asserts the sparse-readback
contract: one query fetches O(P) floats (1 padded row), not O(M*P).

Usage: python benchmarks/query_engine.py [--reps 30] [--tpu]
       [--out QUERY_ENGINE_r7.json]
Prints one JSON object (save as QUERY_ENGINE_r*.json); importable as
``run(...)`` for tests/capture and for bench.py's headline extras.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

from bench import HBM_PEAK_BYTES_PER_S

# (label, num_metrics, bucket_limit, tiers) — same grid as
# interval_commit.py: the 10k point shrinks buckets and tier depth so
# the rings fit everywhere; the contest is query dispatch and readback
# volume, not ring HBM.
CONFIGS = [
    ("1", 1, 4096, ((60, 1), (60, 60), (24, 3600))),
    ("16", 16, 4096, ((60, 1), (60, 60), (24, 3600))),
    ("10000", 10_000, 256, ((8, 1), (4, 8))),
]

WARM_INTERVALS = 6  # committed before any timing starts


def _intervals(rng, n, num_metrics, bucket_limit, cells_per_metric=24):
    """Pre-built sparse interval payloads ({name: {bucket: count}}) —
    identical streams for both contenders."""
    t0 = _dt.datetime(2026, 1, 1, tzinfo=_dt.timezone.utc)
    names = [f"m{i}" for i in range(num_metrics)]
    out = []
    for i in range(n):
        hists = {}
        for name in names:
            b = rng.integers(-bucket_limit, bucket_limit, cells_per_metric)
            c = rng.integers(1, 100, cells_per_metric)
            h = {}
            for bb, cc in zip(b, c):
                h[int(bb)] = h.get(int(bb), 0) + int(cc)
            hists[name] = h
        out.append((t0 + _dt.timedelta(seconds=i), hists))
    return out


def _timed(fn, reps):
    lat = []
    for _ in range(reps):
        t1 = time.perf_counter()
        fn()
        lat.append(time.perf_counter() - t1)
    return lat


def _stats_us(lat):
    return {
        "median_us": round(float(np.median(lat)) * 1e6, 1),
        "p99_us": round(float(np.percentile(lat, 99)) * 1e6, 1),
    }


def run(reps: int = 30) -> dict:
    import jax

    from loghisto_tpu.commit import IntervalCommitter
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.metrics import RawMetricSet
    from loghisto_tpu.parallel.aggregator import TPUAggregator
    from loghisto_tpu.window import TimeWheel

    platform = jax.devices()[0].platform
    cap = HBM_PEAK_BYTES_PER_S.get(platform, 4e12)
    result = {
        "metric": "windowed percentile-query latency, snapshot vs recompute",
        "platform": platform,
        "reps": reps,
        "hbm_peak_bytes_per_s": cap,
        "configs": {},
    }
    for label, num_metrics, bucket_limit, tiers in CONFIGS:
        cfg = MetricConfig(bucket_limit=bucket_limit)
        rng = np.random.default_rng(0)
        stream = _intervals(rng, WARM_INTERVALS, num_metrics, bucket_limit)

        def raw_of(entry):
            t, hists = entry
            return RawMetricSet(time=t, counters={}, rates={},
                                histograms=hists, gauges={}, duration=1.0)

        # -- snapshot contender: fused commits publish CDF snapshots --- #
        agg = TPUAggregator(num_metrics=num_metrics, config=cfg)
        wheel = TimeWheel(num_metrics=num_metrics, config=cfg, interval=1.0,
                          tiers=tiers, registry=agg.registry)
        committer = IntervalCommitter(agg, wheel)
        committer.warmup()
        for entry in stream:
            committer.commit(raw_of(entry))
        agg._acc.block_until_ready()
        assert committer.fanout_intervals == 0
        assert wheel.snapshot is not None
        epoch0 = wheel.snapshot.epoch

        # -- recompute baseline: the pre-snapshot locked path ----------- #
        agg2 = TPUAggregator(num_metrics=num_metrics, config=cfg)
        wheel2 = TimeWheel(num_metrics=num_metrics, config=cfg, interval=1.0,
                           tiers=tiers, registry=agg2.registry,
                           snapshots=False)
        for entry in stream:
            wheel2.push(raw_of(entry))

        # warm both query paths (glob cache, jit compiles) off the clock
        base_ws = wheel2.query("*")
        snap_ws = wheel.query("*")
        assert base_ws.metrics.keys() == snap_ws.metrics.keys()
        wheel.query("m0")
        wheel2.query("m0")

        recompute = _timed(lambda: wheel2.query("*"), reps)
        assert wheel2.query_snapshot_hits == 0

        # warm-cached: epoch unchanged -> host result-cache hit, zero
        # dispatch (this is what repeat scrapes within an interval pay)
        hits0 = wheel.query_result_cache_hits
        warm = _timed(lambda: wheel.query("*"), reps)
        assert wheel.query_result_cache_hits - hits0 == reps

        # fresh-dispatch: clearing the host result cache forces the one
        # sparse gather dispatch (what the first query after a commit
        # pays); the plan/glob caches stay warm, as they would live
        def fresh():
            wheel._result_cache.clear()
            wheel.query("*")
        dispatch = _timed(fresh, reps)

        # sparse single-metric leg + the O(P)-readback contract
        rows0 = wheel.query_rows_fetched

        def sparse():
            wheel._result_cache.clear()
            wheel.query("m0")
        sparse_lat = _timed(sparse, reps)
        rows_per_query = (wheel.query_rows_fetched - rows0) / reps
        assert rows_per_query < num_metrics or num_metrics == 1, (
            f"sparse query fetched {rows_per_query} rows/query at "
            f"{num_metrics} metrics — readback is O(M*P), not O(P)"
        )
        assert wheel.snapshot.epoch == epoch0  # nothing committed mid-run
        assert wheel.query_fallbacks == 0

        rec_med = float(np.median(recompute))
        rec_p99 = float(np.percentile(recompute, 99))
        warm_p99 = float(np.percentile(warm, 99))
        disp_p99 = float(np.percentile(dispatch, 99))

        # plausibility: the recompute merges every written ring slot, so
        # its implied ring bandwidth must stay under the platform
        # roofline — a faster-than-physics baseline means broken timing,
        # and a speedup against it would be garbage
        ti = base_ws.tier
        t = wheel2._tiers[ti]
        ring_bytes = (
            int(t.written.sum()) * num_metrics * cfg.num_buckets * 4
        )
        implied_bw = ring_bytes / max(rec_med, 1e-9)
        suspect = implied_bw > cap
        if suspect:
            print(
                f"query_engine: implied recompute bandwidth "
                f"{implied_bw:.3e} B/s exceeds the {platform} roofline cap "
                f"{cap:.3e}; withholding the speedup headline for config "
                f"{label}", file=sys.stderr,
            )
        result["configs"][label] = {
            "num_metrics": num_metrics,
            "num_buckets": cfg.num_buckets,
            "tiers": [list(t_) for t_ in tiers],
            "tier_queried": ti,
            "recompute_full_glob": _stats_us(recompute),
            "snapshot_warm_cached_full_glob": _stats_us(warm),
            "snapshot_dispatch_full_glob": _stats_us(dispatch),
            "snapshot_dispatch_one_metric": _stats_us(sparse_lat),
            "sparse_rows_per_one_metric_query": rows_per_query,
            "ring_bytes_merged_per_recompute": ring_bytes,
            "implied_recompute_bytes_per_s": round(implied_bw, 1),
            "suspect": suspect,
            "speedup_warm_cached": (
                None if suspect else round(rec_p99 / max(warm_p99, 1e-9), 1)
            ),
            "speedup_dispatch": (
                None if suspect else round(rec_p99 / max(disp_p99, 1e-9), 1)
            ),
        }
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reps", type=int, default=30)
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing CPU")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = run(reps=args.reps)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
