"""Observability receipts (the ISSUE 9 tentpole): what the span
recorder actually costs on the hot path, and what the pipeline's own
stage timings look like once it observes itself.

Two parts:

  * firehose contender — ``run_firehose`` with ``recorder=None`` vs a
    live ``SpanRecorder``: the recorder adds one ``perf_counter_ns``
    pair + one ring store per dispatch step, so throughput loss is the
    honest price of always-on observability.  The acceptance criterion
    is < 2% (``obs_overhead_pct``).  Contenders alternate rep by rep so
    host-speed drift (this shared host swings >2x between windows; see
    bench.py's ``cpu_calibration_mb_s``) hits both sides equally.
  * pipeline stage decomposition — a fused ``TPUMetricSystem`` with
    ``observability=ObsConfig(...)`` driven for a few seconds; per-stage
    p99s come straight from the span ring (the same data Perfetto
    renders), and ``pipeline_stage_p99_us`` is the end-to-end
    ``commit.e2e`` p99.

The roofline plausibility guard marks a throughput whose implied ingest
bandwidth (4 B/sample device-side) exceeds the platform cap as suspect
rather than reporting a faster-than-physics overhead number.

Usage: python benchmarks/obs_overhead.py [--reps 4] [--seconds 1.5]
       [--tpu] [--out OBS_OVERHEAD_r9.json]
Prints one JSON object (save as OBS_OVERHEAD_r*.json); importable as
``run(...)`` for tests/capture and for bench.py's ``obs_overhead_pct``
and ``pipeline_stage_p99_us`` headline fields.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np

from bench import HBM_PEAK_BYTES_PER_S

NUM_METRICS = 1024
BATCH = 1 << 16
BUCKET_LIMIT = 1024


def _firehose_rate(seconds: float, recorder) -> float:
    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.firehose import run_firehose

    summary = run_firehose(
        num_metrics=NUM_METRICS, batch=BATCH, seconds=seconds,
        interval=max(seconds / 3.0, 0.2),
        config=MetricConfig(bucket_limit=BUCKET_LIMIT),
        out=io.StringIO(), recorder=recorder,
    )
    return float(summary["samples_per_s"])


def _pipeline_stages(seconds: float) -> dict:
    """Drive a fused self-observing system and read the stage p99s out
    of its own span ring."""
    from loghisto_tpu.obs import ObsConfig
    from loghisto_tpu.system import TPUMetricSystem

    ms = TPUMetricSystem(
        interval=0.1, sys_stats=False, num_metrics=64,
        retention=((8, 1),), commit="fused",
        observability=ObsConfig(capacity=8192),
    )
    try:
        ms.start()
        deadline = time.monotonic() + seconds
        rng = np.random.default_rng(0)
        while time.monotonic() < deadline:
            for v in rng.exponential(500.0, 200):
                ms.histogram("bench.lat", float(v))
            time.sleep(0.005)
        # let the last interval commit before reading the ring
        t0 = time.monotonic()
        while ms.committer.intervals_committed < 2 \
                and time.monotonic() - t0 < 5.0:
            time.sleep(0.02)
    finally:
        ms.stop()
    by_stage: dict = {}
    for s in ms.obs.spans():
        by_stage.setdefault(s.stage, []).append(s.duration_us)
    return {
        stage: {
            "count": len(d),
            "p50_us": round(float(np.percentile(d, 50)), 1),
            "p99_us": round(float(np.percentile(d, 99)), 1),
        }
        for stage, d in sorted(by_stage.items())
    }


def run(reps: int = 4, seconds: float = 1.5) -> dict:
    import jax

    from loghisto_tpu.obs import SpanRecorder

    platform = jax.devices()[0].platform
    cap = HBM_PEAK_BYTES_PER_S.get(platform, 4e12)

    # alternate the contenders so host-speed drift cancels
    off_rates, on_rates = [], []
    recorders = []
    for _ in range(reps):
        off_rates.append(_firehose_rate(seconds, None))
        rec = SpanRecorder(capacity=8192)
        on_rates.append(_firehose_rate(seconds, rec))
        recorders.append(rec)
    off_med = float(np.median(off_rates))
    on_med = float(np.median(on_rates))
    overhead_pct = (off_med - on_med) / max(off_med, 1e-9) * 100.0

    implied_bw = off_med * 4.0  # 4 B/sample reaches the device kernel
    suspect = implied_bw > cap
    if suspect:
        print(
            f"obs_overhead: implied ingest bandwidth {implied_bw:.3e} "
            f"B/s exceeds the {platform} roofline cap {cap:.3e}; "
            "marking suspect", file=sys.stderr,
        )

    spans_recorded = sum(r.recorded for r in recorders)
    stages = _pipeline_stages(max(seconds, 1.0) * 2.0)
    e2e = stages.get("commit.e2e", {})
    return {
        "metric": "span-recorder cost on the firehose + pipeline stage p99s",
        "platform": platform,
        "reps": reps,
        "seconds_per_rep": seconds,
        "num_metrics": NUM_METRICS,
        "batch": BATCH,
        "hbm_peak_bytes_per_s": cap,
        "firehose_samples_per_s_recorder_off": round(off_med, 1),
        "firehose_samples_per_s_recorder_on": round(on_med, 1),
        "obs_overhead_pct": round(overhead_pct, 2),
        "obs_overhead_budget_pct": 2.0,
        "spans_recorded": spans_recorded,
        "implied_ingest_bytes_per_s": round(implied_bw, 1),
        "suspect": suspect,
        "pipeline_stages": stages,
        "pipeline_stage_p99_us": e2e.get("p99_us"),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reps", type=int, default=4)
    parser.add_argument("--seconds", type=float, default=1.5)
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing CPU")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = run(reps=args.reps, seconds=args.seconds)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
