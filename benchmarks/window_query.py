"""Window-query latency vs window length (ISSUE 1 acceptance): shows
the timewheel query is ONE device reduction over the ring, not a
per-interval host loop — latency must scale sublinearly (effectively
flat) in the window length, because every query merges the same
fixed-shape ring under a different slot mask.

A host-side per-interval loop over the same data is measured alongside
as the contrast: its cost grows linearly with the window, the wheel's
does not.

Usage: python benchmarks/window_query.py [--metrics 1024]
       [--bucket-limit 4096] [--slots 64] [--reps 5] [--out FILE]
Prints one JSON object (save as WINDOW_QUERY_r*.json); importable as
``run(...)`` for tests/capture.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np


def run(num_metrics: int = 1024, bucket_limit: int = 4_096,
        slots: int = 64, samples_per_interval: int = 10_000,
        reps: int = 5) -> dict:
    import jax

    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.metrics import RawMetricSet
    from loghisto_tpu.ops.codec import compress_np
    from loghisto_tpu.window import TierSpec, TimeWheel

    cfg = MetricConfig(bucket_limit=bucket_limit)
    platform = jax.devices()[0].platform
    wheel = TimeWheel(
        num_metrics=num_metrics, config=cfg, interval=1.0,
        tiers=[TierSpec(slots, 1)],
    )

    # fill the ring: every interval scatters a fresh lognormal batch over
    # a handful of metric names (the sparse raw path, like live traffic)
    rng = np.random.default_rng(0)
    names = [f"m{i}" for i in range(8)]
    t0 = _dt.datetime(2026, 1, 1, tzinfo=_dt.timezone.utc)
    sparse_history = []  # per-interval {name: {bucket: count}} for the loop
    for i in range(slots):
        hists = {}
        for name in names:
            vals = rng.lognormal(8.0, 2.0, samples_per_interval // len(names))
            buckets = compress_np(vals, cfg.precision)
            ub, cnt = np.unique(buckets, return_counts=True)
            hists[name] = {int(b): int(c) for b, c in zip(ub, cnt)}
        sparse_history.append(hists)
        wheel.push(RawMetricSet(
            time=t0 + _dt.timedelta(seconds=i), counters={}, rates={},
            histograms=hists, gauges={}, duration=1.0,
        ))

    ps = (0.5, 0.99)
    windows = [w for w in (1, 2, 4, 8, 16, 32, slots) if w <= slots]
    result = {
        "metric": "window query latency vs window length",
        "platform": platform,
        "merge_path": wheel.merge_path,
        "num_metrics": num_metrics,
        "num_buckets": cfg.num_buckets,
        "slots": slots,
        "reps": reps,
        "queries": {},
    }
    for w in windows:
        wheel.query("*", float(w), ps)  # compile + warm this mask shape
        times = []
        for _ in range(reps):
            t1 = time.perf_counter()
            res = wheel.query("*", float(w), ps)
            times.append(time.perf_counter() - t1)
        assert res.slots == w

        # contrast: per-interval host loop (sparse merge + numpy stats)
        t1 = time.perf_counter()
        merged: dict = {}
        for hists in sparse_history[-w:]:
            for name, buckets in hists.items():
                dst = merged.setdefault(name, {})
                for b, c in buckets.items():
                    dst[b] = dst.get(b, 0) + c
        t_loop = time.perf_counter() - t1

        result["queries"][str(w)] = {
            "device_median_ms": round(float(np.median(times)) * 1e3, 3),
            "host_loop_merge_ms": round(t_loop * 1e3, 3),
        }

    qs = result["queries"]
    w_lo, w_hi = str(windows[0]), str(windows[-1])
    # headline ratio: device latency growth across a slots-times-wider
    # window; ~1.0 means flat (sublinear), the acceptance bar
    result["device_latency_ratio_max_vs_min_window"] = round(
        qs[w_hi]["device_median_ms"] / qs[w_lo]["device_median_ms"], 2
    )
    result["window_ratio"] = windows[-1] / windows[0]
    result["host_loop_ratio_max_vs_min_window"] = round(
        qs[w_hi]["host_loop_merge_ms"]
        / max(qs[w_lo]["host_loop_merge_ms"], 1e-6), 2
    )
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--metrics", type=int, default=1024)
    parser.add_argument("--bucket-limit", type=int, default=4_096)
    parser.add_argument("--slots", type=int, default=64)
    parser.add_argument("--reps", type=int, default=5)
    parser.add_argument("--tpu", action="store_true",
                        help="keep the configured (TPU) platform instead "
                             "of forcing CPU")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    result = run(num_metrics=args.metrics, bucket_limit=args.bucket_limit,
                 slots=args.slots, reps=args.reps)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
