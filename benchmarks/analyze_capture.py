"""Turn a capture directory into a kernel ranking + dispatch advice.

Usage: python benchmarks/analyze_capture.py TPU_CAPTURE_r2b [...]

Reads each directory's ``device_paths.json`` (written by
benchmarks/tpu_oneshot.py stage 5 / benchmarks/device_paths.py) and
prints, per metric count, the measured ranking plus the winner — then
compares the winners against what ``ops/dispatch.py`` would choose, so
refreshing the dispatch thresholds after a capture is a mechanical
diff-and-edit instead of a judgment call.  Pure stdlib; safe to run
anywhere (no jax import).
"""

from __future__ import annotations

import json
import os
import sys


def _load_choose():
    """Load choose_ingest_path from ops/dispatch.py WITHOUT importing the
    loghisto_tpu package (whose __init__ chain pulls in jax) — the module
    file itself is stdlib-only, which keeps this script runnable on any
    machine holding a copy of the capture."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "loghisto_tpu", "ops", "dispatch.py",
    )
    spec = importlib.util.spec_from_file_location("_lh_dispatch", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.choose_ingest_path


def load(dirname: str) -> dict | None:
    path = os.path.join(dirname, "device_paths.json")
    if not os.path.exists(path):
        print(f"{dirname}: no device_paths.json")
        return None
    with open(path) as f:
        return json.load(f)


def analyze(dirname: str, table: dict) -> None:
    rates: dict[str, float] = table.get("rates", {})
    errors: dict[str, str] = table.get("errors", {})
    by_m: dict[int, list[tuple[float, str]]] = {}
    for key, rate in rates.items():
        name, m = key.rsplit("@", 1)
        by_m.setdefault(int(m), []).append((rate, name))
    print(f"\n== {dirname} (platform={table.get('platform')}, "
          f"mode={table.get('mode')}) ==")
    winners: dict[int, str] = {}
    for m in sorted(by_m):
        ranked = sorted(by_m[m], reverse=True)
        winners[m] = ranked[0][1]
        line = " > ".join(f"{n} {r:.3g}" for r, n in ranked)
        print(f"M={m:<6} {line}")
    for key, err in errors.items():
        print(f"   error {key}: {err}")
    if table.get("platform") != "tpu" or not winners:
        return
    choose_ingest_path = _load_choose()

    print("dispatch check (auto vs measured winner):")
    for m, winner in sorted(winners.items()):
        auto = choose_ingest_path(m, 8193, "tpu")
        # the no-ids pallas row form isn't an (ids, values) candidate;
        # its dispatchable twin is "pallasb"
        mark = "OK" if auto == winner or (
            auto == "pallas" and winner in ("pallas", "pallasb")
        ) else "REVISIT"
        print(f"  M={m:<6} auto={auto:<8} measured={winner:<8} {mark}")


def main() -> int:
    dirs = sys.argv[1:] or sorted(
        d for d in os.listdir(".")
        if d.startswith("TPU_CAPTURE") and os.path.isdir(d)
    )
    if not dirs:
        print("no TPU_CAPTURE* directories here; pass capture dirs as "
              "arguments (e.g. python benchmarks/analyze_capture.py "
              "TPU_CAPTURE_r2b)", file=sys.stderr)
        return 1
    found = False
    for d in dirs:
        table = load(d)
        if table:
            analyze(d, table)
            found = True
    return 0 if found else 1


if __name__ == "__main__":
    raise SystemExit(main())
