"""Turn a capture directory into a kernel ranking + dispatch advice.

Usage: python benchmarks/analyze_capture.py TPU_CAPTURE_r2b [...]
       python benchmarks/analyze_capture.py --emit-thresholds CAPTURE_DIR

Reads each directory's ``device_paths.json`` (written by
benchmarks/tpu_oneshot.py stage 6 / benchmarks/device_paths.py) and
prints, per metric count, the measured ranking plus the winner — then
compares the winners against what ``ops/dispatch.py`` would choose.

``--emit-thresholds`` derives a dispatch threshold table from ONE
capture's winners and writes it to
``loghisto_tpu/ops/dispatch_thresholds.json``, which ``ops/dispatch.py``
loads at import — so refreshing the dispatch policy after a hardware
capture is a committed JSON, not a code edit (VERDICT r2 item 7).
Pure stdlib; safe to run anywhere (no jax import).
"""

from __future__ import annotations

import json
import os
import sys


def _load_dispatch():
    """Load ops/dispatch.py WITHOUT importing the loghisto_tpu package
    (whose __init__ chain pulls in jax) — the module file itself is
    stdlib-only, which keeps this script runnable on any machine holding
    a copy of the capture.  Also the single source of truth for where the
    thresholds file lives (mod.THRESHOLDS_FILE)."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "loghisto_tpu", "ops", "dispatch.py",
    )
    spec = importlib.util.spec_from_file_location("_lh_dispatch", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load(dirname: str) -> dict | None:
    path = os.path.join(dirname, "device_paths.json")
    if not os.path.exists(path):
        print(f"{dirname}: no device_paths.json")
        return None
    with open(path) as f:
        return json.load(f)


def analyze(dirname: str, table: dict) -> dict[int, str]:
    rates: dict[str, float] = table.get("rates", {})
    errors: dict[str, str] = table.get("errors", {})
    by_m: dict[int, list[tuple[float, str]]] = {}
    for key, rate in rates.items():
        name, m = key.rsplit("@", 1)
        by_m.setdefault(int(m), []).append((rate, name))
    print(f"\n== {dirname} (platform={table.get('platform')}, "
          f"mode={table.get('mode')}) ==")
    winners: dict[int, str] = {}
    for m in sorted(by_m):
        ranked = sorted(by_m[m], reverse=True)
        winners[m] = ranked[0][1]
        line = " > ".join(f"{n} {r:.3g}" for r, n in ranked)
        print(f"M={m:<6} {line}")
    for key, err in errors.items():
        print(f"   error {key}: {err}")
    if table.get("platform") != "tpu" or not winners:
        return winners
    choose_ingest_path = _load_dispatch().choose_ingest_path

    # captures record their bucket config; older ones predate the field
    num_buckets = table.get("num_buckets", 8193)
    print("dispatch check (auto vs measured winner):")
    for m, winner in sorted(winners.items()):
        auto = choose_ingest_path(m, num_buckets, "tpu")
        # the no-ids pallas row form isn't an (ids, values) candidate;
        # its dispatchable twin is "pallasb"
        mark = "OK" if auto == winner or (
            auto == "pallas" and winner in ("pallas", "pallasb")
        ) else "REVISIT"
        print(f"  M={m:<6} auto={auto:<8} measured={winner:<8} {mark}")
    return winners


SORT_FAMILY = ("sort", "sortscan")


def derive_thresholds(dirname: str, table: dict,
                      winners: dict[int, str]) -> dict | None:
    """One capture's winners -> the dispatch threshold table
    ops/dispatch.py loads.  Policy shape is fixed (pallas at M=1?,
    sort-family above a crossover, scatter between); this derives the
    numbers.  Returns None when the capture can't support the policy
    (not TPU, or no multi-metric rows)."""
    if table.get("platform") != "tpu":
        print(f"{dirname}: not a TPU capture; no thresholds derived")
        return None
    multi = {m: w for m, w in winners.items() if m > 1}
    if not multi:
        print(f"{dirname}: no multi-metric rows; no thresholds derived")
        return None

    sort_wins = sorted(m for m, w in multi.items() if w in SORT_FAMILY)
    other_wins = sorted(m for m, w in multi.items() if w not in SORT_FAMILY)
    if sort_wins and sort_wins[-1] < max(other_wins, default=0):
        # non-monotone table with sort LOSING at the top of the measured
        # range: a threshold would dispatch sort into a region the capture
        # shows another kernel winning — disable instead of extrapolating
        print(f"{dirname}: WARNING sort-family wins at {sort_wins} but "
              f"loses above (others at {other_wins}); disabling the "
              f"sort-family dispatch region")
        sort_wins = []
    if sort_wins:
        lo = max([m for m in other_wins if m < sort_wins[0]] or [1])
        # geometric midpoint of the measured bracket: the crossover is a
        # ratio phenomenon (duplicate density scales with batch/M).
        # Floor of 2 keeps the value inside the loader's smm > 1 guard
        # (M=1 has its own pallas policy axis).
        sort_min = max(2, int(round((lo * sort_wins[0]) ** 0.5)))
        # which sort formulation won at the high-cardinality rows
        kernel = winners[sort_wins[-1]]
    else:
        sort_min = 1 << 30  # sort-family never measured fastest
        kernel = "sort"

    return {
        "source": dirname,
        "platform": "tpu",
        "num_buckets": table.get("num_buckets", 8193),
        "batch": table.get("batch"),
        "mode": table.get("mode"),
        "winners": {str(m): w for m, w in sorted(winners.items())},
        "sort_min_metrics": sort_min,
        "high_cardinality_kernel": kernel,
        "pallas_single_metric": winners.get(1) in ("pallas", "pallasb"),
    }


def main() -> int:
    argv = sys.argv[1:]
    emit = False
    if "--emit-thresholds" in argv:
        emit = True
        argv = [a for a in argv if a != "--emit-thresholds"]
    dirs = argv or sorted(
        d for d in os.listdir(".")
        if d.startswith("TPU_CAPTURE") and os.path.isdir(d)
    )
    if not dirs:
        print("no TPU_CAPTURE* directories here; pass capture dirs as "
              "arguments (e.g. python benchmarks/analyze_capture.py "
              "TPU_CAPTURE_r2b)", file=sys.stderr)
        return 1
    if emit and len(dirs) != 1:
        print("--emit-thresholds takes exactly one capture directory "
              "(the table must come from a single hardware ranking)",
              file=sys.stderr)
        return 1
    found = False
    for d in dirs:
        table = load(d)
        if table:
            winners = analyze(d, table)
            found = True
            if emit:
                thresholds = derive_thresholds(d, table, winners)
                if thresholds is None:
                    return 1
                out = _load_dispatch().THRESHOLDS_FILE
                with open(out, "w") as f:
                    json.dump(thresholds, f, indent=1)
                    f.write("\n")
                print(f"\nwrote {out}:")
                print(json.dumps(thresholds, indent=1))
    return 0 if found else 1


if __name__ == "__main__":
    raise SystemExit(main())
