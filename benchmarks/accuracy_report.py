"""Accuracy evidence: measured percentile error of every estimator across
distribution shapes, against exact np.quantile ground truth.

Usage: python benchmarks/accuracy_report.py  (writes markdown to stdout)
"""

from __future__ import annotations

import numpy as np

# runnable from anywhere: add the repo root to sys.path
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

QS = np.array([0.5, 0.9, 0.99, 0.999, 0.9999], dtype=np.float32)
N = 200_000


def distributions(rng):
    yield "uniform(0,1000)", rng.uniform(0, 1000, N)
    yield "normal(100,15)", rng.normal(100, 15, N)
    yield "lognormal(5,2)", rng.lognormal(5, 2, N)
    yield "exponential(1e6)", rng.exponential(1e6, N)
    yield "pareto(a=1.5)x1e3", (rng.pareto(1.5, N) + 1) * 1e3
    yield "bimodal", np.concatenate(
        [rng.normal(10, 1, N // 2), rng.normal(1e4, 1e3, N // 2)]
    )


def main():
    import jax

    # accuracy is platform-independent; default to CPU without touching
    # the (possibly wedged) TPU tunnel unless explicitly requested
    if not _os.environ.get("LOGHISTO_REPORT_TPU"):
        jax.config.update("jax_platforms", "cpu")

    from loghisto_tpu.config import MetricConfig
    from loghisto_tpu.models import LogHistogram, moments, tdigest

    rng = np.random.default_rng(0)
    print("| distribution | estimator | " +
          " | ".join(f"p{q:g}" for q in QS) + " |")
    print("|---" * (len(QS) + 2) + "|")
    for label, data in distributions(rng):
        data = np.abs(data).astype(np.float32)  # latency-like
        truth = np.quantile(data, QS)

        # log-bucket histogram (the <=1% contract)
        h = LogHistogram.empty(MetricConfig(bucket_limit=4096))
        h = h.insert(data)
        hist_q = h.statistics(QS)["percentiles"]

        # t-digest (range-free)
        m, w = tdigest.empty()
        for chunk in np.array_split(data, 10):
            m, w = tdigest.insert(m, w, chunk)
        td_q = np.asarray(tdigest.quantile(m, w, QS))

        # moments (O(1) state)
        st = moments.empty()
        for chunk in np.array_split(data, 10):
            st = moments.insert(st, chunk)
        mo_q = np.asarray(moments.quantile(st, QS))

        for est, qvals in (
            ("loghist", hist_q), ("tdigest", td_q), ("moments", mo_q)
        ):
            errs = np.abs(qvals / np.maximum(truth, 1e-12) - 1)
            cells = " | ".join(f"{e:.2%}" for e in errs)
            print(f"| {label} | {est} | {cells} |")


if __name__ == "__main__":
    main()
