#!/bin/bash
# Persistent TPU capture watcher (VERDICT r1 item 1): keep attempting a
# full single-process capture until one healthy tunnel window succeeds.
#   bash benchmarks/tpu_watch.sh [tag] [max_hours]
set -u
cd "$(dirname "$0")/.."
TAG="${1:-r2}"
MAX_HOURS="${2:-11}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
ATTEMPT=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  OUT="tpu_results_${TAG}_a${ATTEMPT}"
  echo "=== attempt $ATTEMPT -> $OUT ($(date)) ==="
  timeout 3900 python benchmarks/tpu_oneshot.py "$OUT"
  rc=$?
  if [ -f "$OUT/SUCCESS" ]; then
    echo "=== CAPTURED on attempt $ATTEMPT; results in $OUT ==="
    exit 0
  fi
  # Preserve any per-stage results a partial run flushed before the
  # tunnel wedged — stage JSONs are the whole point of the capture
  if ls "$OUT"/*.json >/dev/null 2>&1; then
    mkdir -p TPU_CAPTURE_partial
    cp -n "$OUT"/* TPU_CAPTURE_partial/ 2>/dev/null
    echo "=== attempt $ATTEMPT partial: kept stage results in TPU_CAPTURE_partial ==="
  fi
  # rc=2: init reached a non-TPU platform; rc=124: timeout/wedge
  echo "=== attempt $ATTEMPT failed rc=$rc; sleeping 300s ==="
  rm -rf "$OUT" 2>/dev/null
  sleep 300
done
echo "=== gave up after $ATTEMPT attempts ==="
exit 1
