#!/bin/bash
# Persistent TPU capture watcher (VERDICT r1 item 1): keep attempting a
# full single-process capture until one healthy tunnel window succeeds.
#   bash benchmarks/tpu_watch.sh [tag] [max_hours]
set -u
cd "$(dirname "$0")/.."
TAG="${1:-r2}"
MAX_HOURS="${2:-11}"
# SINGLE INSTANCE: rounds 3-5 each left their 11h watcher running into
# the next round, so up to four watchers' PJRT init attempts stomped the
# one tunnel concurrently — every attempt wedged (round 2's lone watcher
# captured fine).  An flock'd lockfile enforces it now: pgrep -f matched
# any cmdline QUOTING the script name (editors, tail -f, the launching
# bash -c) and kill -9'd innocents, and two racing starts could each
# survive the other's sweep.  The lock is kernel-owned, race-free, and
# releases itself however this process dies.
LOCKFILE="benchmarks/.tpu_watch.lock"
PIDFILE="benchmarks/.tpu_watch.pid"
exec 200>"$LOCKFILE"
if ! flock -n 200; then
  echo "tpu_watch: another watcher holds $LOCKFILE (pid $(cat "$PIDFILE" 2>/dev/null || echo '?')); exiting" >&2
  exit 1
fi
echo "$$" > "$PIDFILE"
# A previous watcher's capture child can survive its parent (setsid put
# it in its own process group).  Its pgid is recorded in the pidfile's
# companion — kill exactly that group, never a pgrep guess.
CHILDFILE="benchmarks/.tpu_oneshot.pgid"
if OLDPG=$(cat "$CHILDFILE" 2>/dev/null) && [ -n "$OLDPG" ]; then
  kill -TERM -- "-$OLDPG" 2>/dev/null
  sleep 2
  kill -9 -- "-$OLDPG" 2>/dev/null
fi
trap 'rm -f "$PIDFILE" "$CHILDFILE"' EXIT
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
ATTEMPT=0
# A wedged tunnel hangs PJRT init ~25 min before failing; a HEALTHY init
# completes in well under a minute.  Kill attempts still stuck in init
# after INIT_TIMEOUT so the retry cadence tracks short healthy windows
# (one init per process either way — the probe IS the capture).
INIT_TIMEOUT=360
# Relay-port probe (round 5 diagnosis): jax.devices() goes through the
# axon loopback relay on 127.0.0.1:8083 (axon/register/pjrt.py:188);
# when NOTHING is listening there (netstat showed no listener for the
# whole of rounds 3-5), a PJRT attempt can only burn its 6-minute init
# window.  Poll the port every 20s and attempt the moment it opens —
# reaction time drops from one 11-minute blind cycle to ~20s.  A blind
# attempt still fires every BLIND_EVERY seconds in case the probe
# assumption is ever wrong.
relay_up() {
  (exec 3<>/dev/tcp/127.0.0.1/8083) 2>/dev/null && { exec 3>&-; return 0; }
  return 1
}
BLIND_EVERY=3600
LAST_ATTEMPT=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  NOW=$(date +%s)
  if ! relay_up && [ $((NOW - LAST_ATTEMPT)) -lt "$BLIND_EVERY" ]; then
    sleep 20
    continue
  fi
  LAST_ATTEMPT=$NOW
  ATTEMPT=$((ATTEMPT + 1))
  OUT="tpu_results_${TAG}_a${ATTEMPT}"
  LOG="${OUT}.log"
  echo "=== attempt $ATTEMPT -> $OUT ($(date)) ==="
  # own process group so the wedge-kill can reach the python child even
  # when it is stuck inside an uninterruptible PJRT C call (killing just
  # the timeout wrapper would orphan it, still holding the device)
  setsid timeout 3900 python benchmarks/tpu_oneshot.py "$OUT" > "$LOG" 2>&1 &
  PID=$!
  # setsid made the child its own group leader: pgid == pid.  Record it
  # so the NEXT watcher can reap a survivor without pattern-matching.
  echo "$PID" > "$CHILDFILE"
  WAITED=0
  while kill -0 "$PID" 2>/dev/null; do
    if [ "$WAITED" -ge "$INIT_TIMEOUT" ] && \
       ! grep -q 'platform=' "$LOG" 2>/dev/null; then
      echo "=== attempt $ATTEMPT: init still wedged after ${WAITED}s; killing ==="
      kill -TERM -- "-$PID" 2>/dev/null
      sleep 2
      kill -9 -- "-$PID" 2>/dev/null
      break
    fi
    sleep 15
    WAITED=$((WAITED + 15))
  done
  wait "$PID" 2>/dev/null
  rc=$?
  rm -f "$CHILDFILE"
  tail -5 "$LOG" 2>/dev/null
  if [ -f "$OUT/SUCCESS" ]; then
    echo "=== CAPTURED on attempt $ATTEMPT; results in $OUT ==="
    exit 0
  fi
  # Preserve any per-stage results a partial run flushed before the
  # tunnel wedged — stage JSONs are the whole point of the capture
  if ls "$OUT"/*.json >/dev/null 2>&1; then
    mkdir -p TPU_CAPTURE_partial
    cp -n "$OUT"/* TPU_CAPTURE_partial/ 2>/dev/null
    echo "=== attempt $ATTEMPT partial: kept stage results in TPU_CAPTURE_partial ==="
  fi
  # rc=2: init reached a non-TPU platform; rc=124: timeout/wedge
  echo "=== attempt $ATTEMPT failed rc=$rc; back to relay probe ==="
  rm -rf "$OUT" "$LOG" 2>/dev/null
  sleep 30
done
echo "=== gave up after $ATTEMPT attempts ==="
exit 1
