"""Host-fed writer scaling of the sharded cell store (VERDICT r3 item 5).

The reference's pitch is many writer threads folding concurrently
(metrics.go:273-295: RWMutex + per-sample atomic).  This framework's
host preagg tier is `ShardedCellStore`: K tables, each behind its own
lock, writers sticky-assigned to shards, and the C fold releasing the
GIL.  On a multi-core host that design turns the ~38ns/sample hash
probe into per-core scaling; THIS container has one core, so the
measurable claims are narrower and stated as such:

 1. aggregate throughput must NOT collapse as writers are added
    (a single shared table would serialize on one lock and pay
    convoy overhead; sharding keeps the locks uncontended), and
 2. the single-shard-vs-sharded comparison isolates the lock/probe
    split: same probe work, different contention.

Usage: python benchmarks/writer_scaling.py [--samples-per-thread N]
       [--out FILE]
Prints one JSON object; importable as ``run(...)``.
"""

from __future__ import annotations

import argparse
import json
import os as _os
import sys as _sys
import threading
import time

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import numpy as np


def _fold_run(store_factory, n_threads: int, samples_per_thread: int,
              batch: int = 65_536) -> dict:
    """All threads fold pre-generated batches concurrently; wall time is
    measured from the barrier release to the last join."""
    from loghisto_tpu import _native  # noqa: F401  (ensures lib builds)

    store = store_factory()
    rng = np.random.default_rng(3)
    # pre-generate one batch set per thread OUTSIDE the timed region;
    # Zipf ids concentrate probes on hot cells like a real stream
    per_thread = []
    n_batches = samples_per_thread // batch
    for t in range(n_threads):
        bs = []
        for b in range(n_batches):
            ids = ((rng.zipf(1.3, batch) - 1) % 10_000).astype(np.int32)
            vals = rng.lognormal(8, 2, batch).astype(np.float32)
            bs.append((ids, vals))
        per_thread.append(bs)

    barrier = threading.Barrier(n_threads + 1)
    done = []

    def worker(t: int) -> None:
        batches = per_thread[t]
        barrier.wait()
        t0 = time.perf_counter()
        for ids, vals in batches:
            got = store.add(ids, vals)
            assert got == len(ids)
        done.append(time.perf_counter() - t0)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for th in threads:
        th.start()
    barrier.wait()
    t0 = time.perf_counter()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0

    total = n_threads * n_batches * batch
    drained = store.drain() if hasattr(store, "drain") else None
    conserved = (
        int(drained[2].sum()) == total if drained is not None else None
    )
    if hasattr(store, "close"):
        store.close()
    return {
        "threads": n_threads,
        "total_samples": total,
        "wall_s": round(wall, 4),
        "agg_samples_per_s": round(total / wall, 1),
        "ns_per_sample_aggregate": round(wall / total * 1e9, 2),
        "counts_conserved": conserved,
    }


def run(samples_per_thread: int = 4 << 20) -> dict:
    from loghisto_tpu._native import CellStore, ShardedCellStore

    result = {
        "cpu_count": _os.cpu_count(),
        "note": (
            "1-core container: per-core SPEEDUP is not measurable here; "
            "the claims under test are (a) no contention collapse as "
            "writers are added and (b) the sharded-vs-single-table "
            "lock-contention split at equal probe work."
        ),
        "sharded": [],
        "single_table": [],
    }
    for n in (1, 2, 4, 8):
        result["sharded"].append(_fold_run(
            lambda: ShardedCellStore(bucket_limit=4096, num_shards=8),
            n, samples_per_thread,
        ))
    # single shared table: every writer serializes on ONE lock (the
    # GIL-released C fold makes this a real lock, not a GIL artifact)
    class _OneLockStore:
        def __init__(self):
            self._s = CellStore(bucket_limit=4096)
            self._lock = threading.Lock()

        def add(self, ids, vals):
            with self._lock:
                return self._s.add(ids, vals)

        def drain(self):
            return self._s.drain()

        def close(self):
            self._s.close()

    for n in (1, 8):
        result["single_table"].append(
            _fold_run(_OneLockStore, n, samples_per_thread)
        )
    base = result["sharded"][0]["agg_samples_per_s"]
    worst = min(r["agg_samples_per_s"] for r in result["sharded"])
    result["max_collapse_vs_1thread"] = round(base / worst, 3)
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--samples-per-thread", type=int, default=4 << 20)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)
    result = run(samples_per_thread=args.samples_per_thread)
    text = json.dumps(result, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
